"""Shared-memory object transport between the driver and pool workers.

TPU-native analogue of the plasma store (reference:
src/ray/object_manager/plasma/store_runner.h, object_store.h,
client.h fd-passing): objects are serialized once into a POSIX
shared-memory segment (multiprocessing.shared_memory) and mapped
read-only by any process that needs them — worker-to-worker argument
passing never copies through the driver.

The driver owns the directory (object_id -> segment descriptor), which
plays the role of the ownership-based object directory
(src/ray/object_manager/ownership_based_object_directory.h). Workers
hold an open-segment cache so repeated gets of the same object reuse
the mapping.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from multiprocessing import shared_memory
from typing import Any

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID


@dataclass(frozen=True)
class ShmDescriptor:
    """Where an object lives: segment name + payload size."""

    name: str
    size: int


@dataclass(frozen=True)
class ArenaDescriptor:
    """An object resident in the shared arena (plasma-lite,
    _native/plasma_store.cpp): 16-byte key + payload size. ``name`` is a
    sentinel so segment-oriented call sites (close_segment) no-op."""

    key: bytes
    size: int
    name: str = "<arena>"


@dataclass(frozen=True)
class PeerArenaDescriptor:
    """An object resident in ANOTHER process's arena on this host
    (same-host plane): the arena's shm name + object key. Resolved by
    attaching the peer arena read-only (ArenaStore.attach) and copying
    the payload out — the holder's lease pin keeps the bytes valid
    while the copy runs, and the copy (matching local ArenaDescriptor
    semantics) keeps deserialized views valid after lease release.
    ``name`` is a sentinel so segment call sites no-op."""

    arena: str
    key: bytes
    size: int
    name: str = "<peer-arena>"


def untrack(seg: shared_memory.SharedMemory) -> None:
    """Remove a segment from this process's resource tracker.

    Python's tracker auto-unlinks registered segments at process exit —
    a worker exiting would delete objects the driver still serves. So
    workers untrack segments they create (the driver adopts them), and
    the driver `track`s adopted ones, keeping exactly one registration
    alive until the driver's unlink (which unregisters internally).
    """
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass  # private tracker API may change shape


def _defuse(seg: shared_memory.SharedMemory) -> None:
    """Make a segment's close()/__del__ a no-op after a BufferError.

    Live user views still reference the mapping, so it cannot be closed;
    the mapping is deliberately leaked until process exit (the kernel
    reclaims it) instead of raising "Exception ignored in __del__" noise
    at interpreter shutdown. Touches CPython internals knowingly.
    """
    try:
        seg._buf = None
        seg._mmap = None
    except Exception:
        pass  # private segment fields may change shape


def track(seg: shared_memory.SharedMemory) -> None:
    """Register an adopted segment with this process's tracker, making
    the later ``unlink()`` (which unregisters) symmetric and giving
    crash-cleanup for adopted segments."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.register(seg._name, "shared_memory")
    except Exception:
        pass  # private tracker API may change shape


class ShmObjectWriter:
    """Create-then-seal protocol (plasma's Create/Seal)."""

    @staticmethod
    def put_serialized(header, buffers,
                       size: int) -> tuple[ShmDescriptor,
                                           shared_memory.SharedMemory]:
        seg = shared_memory.SharedMemory(create=True, size=max(size, 1))
        serialization.write_framed(seg.buf, header, buffers)
        return ShmDescriptor(seg.name, size), seg

    @staticmethod
    def put(value: Any) -> tuple[ShmDescriptor, shared_memory.SharedMemory]:
        header, buffers = serialization.serialize(value)
        size = serialization.framed_size(header, buffers)
        return ShmObjectWriter.put_serialized(header, buffers, size)

    @staticmethod
    def put_arena_serialized(arena, key: bytes, header, buffers,
                             size: int) -> "ArenaDescriptor | None":
        """Write pre-serialized framed data into the arena under ``key``,
        sealed PINNED (one reference owned by the registering directory;
        ShmDirectory.free unpins). Returns None when the arena is absent
        or full — the caller falls back to a dedicated segment."""
        if arena is None:
            return None
        view = arena.create_for_write(key, size)
        if view is None:
            return None
        serialization.write_framed(view, header, buffers)
        arena.seal_pinned(key)
        return ArenaDescriptor(key, size)



class ShmClient:
    """Per-process reader with an open-segment cache.

    Deserialized values view the mapping zero-copy, so a segment stays
    open (referenced here) for the life of the process once read.
    ``close_segment`` drops the mapping when the driver frees an object.
    """

    def __init__(self, untrack_on_attach: bool = False):
        self._lock = threading.Lock()
        self._segments: dict[str, shared_memory.SharedMemory] = {}
        self._arena = None
        # Python 3.12 registers segments with the resource tracker on
        # ATTACH as well as create. Worker clients never unlink, so they
        # untrack attaches (else their tracker warns/unlinks at exit).
        # The driver's client shares its process with ShmDirectory —
        # whose unlink() unregisters — so it must NOT untrack, or the
        # register/unregister pairing breaks (tracker KeyError noise).
        self._untrack_on_attach = untrack_on_attach
        # Segments whose mappings still have live views at close time;
        # referenced here so __del__ never runs on them.
        self._leaked: list[shared_memory.SharedMemory] = []
        # Cached attachments to peer-owned arenas (same-host plane),
        # created lazily on the first PeerArenaDescriptor resolve.
        self._peer_arenas = None

    def set_arena(self, arena) -> None:
        self._arena = arena

    def get(self, desc: "ShmDescriptor | ArenaDescriptor") -> Any:
        if isinstance(desc, PeerArenaDescriptor):
            with self._lock:
                if self._peer_arenas is None:
                    from ray_tpu._private.same_host import (
                        PeerArenaRegistry,
                    )

                    self._peer_arenas = PeerArenaRegistry()
                registry = self._peer_arenas
            view = registry.view(desc.arena, desc.key)
            if view is None:
                raise KeyError(
                    f"peer-arena object {desc.key.hex()} unavailable "
                    f"in {desc.arena}")
            # One memcpy out of the peer arena: the copy owns the
            # memory, so deserialized zero-copy views survive the
            # holder releasing its lease pin later.
            return serialization.deserialize_from_buffer(
                memoryview(bytes(view[:desc.size])))
        if isinstance(desc, ArenaDescriptor):
            if self._arena is None:
                raise RuntimeError("arena object but no arena attached")
            blob = self._arena.get_bytes(desc.key)
            if blob is None:
                raise KeyError(
                    f"arena object {desc.key.hex()} evicted or deleted")
            # The copy (get_bytes) owns the memory, so zero-copy views
            # from deserialization stay valid after arena eviction.
            return serialization.deserialize_from_buffer(memoryview(blob))
        with self._lock:
            seg = self._segments.get(desc.name)
            if seg is None:
                seg = shared_memory.SharedMemory(name=desc.name)
                if self._untrack_on_attach:
                    untrack(seg)
                self._segments[desc.name] = seg
        return serialization.deserialize_from_buffer(seg.buf[:desc.size])

    def close_segment(self, name: str) -> None:
        with self._lock:
            seg = self._segments.pop(name, None)
        if seg is not None:
            try:
                seg.close()
            except BufferError:
                # A live numpy view still references the mapping; keep it
                # open rather than invalidating user data.
                with self._lock:
                    self._segments[name] = seg

    def close_all(self) -> None:
        with self._lock:
            segments = list(self._segments.items())
            self._segments.clear()
            peer_arenas, self._peer_arenas = self._peer_arenas, None
        if peer_arenas is not None:
            peer_arenas.close_all()
        for _, seg in segments:
            try:
                seg.close()
            except BufferError:
                # Live views remain: leak the mapping until process exit.
                _defuse(seg)
                with self._lock:
                    self._leaked.append(seg)


class ShmDirectory:
    """Driver-side registry of shm-resident objects (owner directory).

    Tracks which segments exist so they can be unlinked exactly once at
    free/shutdown (POSIX shm persists until unlinked — leaking segments
    outlives the process).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_object: dict[ObjectID, "ShmDescriptor | ArenaDescriptor"] = {}
        self._owned: dict[str, shared_memory.SharedMemory] = {}
        self._leaked: list[shared_memory.SharedMemory] = []
        self._arena = None

    def set_arena(self, arena) -> None:
        self._arena = arena

    def register_arena(self, object_id: ObjectID,
                       desc: ArenaDescriptor) -> None:
        """Record an arena-resident object.

        The object arrives sealed PINNED (seal_pinned: refcount 1 from
        creation, so it was never evictable in transit); the directory
        takes over that reference and drops it in ``free``.
        """
        with self._lock:
            self._by_object[object_id] = desc

    def register(self, object_id: ObjectID, desc: ShmDescriptor,
                 segment: shared_memory.SharedMemory | None = None) -> None:
        with self._lock:
            self._by_object[object_id] = desc
            if segment is not None:
                self._owned[desc.name] = segment

    def adopt(self, object_id: ObjectID, desc: ShmDescriptor) -> None:
        """Record a worker-created segment; the driver takes ownership of
        unlinking it (the worker process may exit first)."""
        try:
            seg = shared_memory.SharedMemory(name=desc.name)
        except FileNotFoundError:
            return
        track(seg)  # the creating worker untracked; ownership moves here
        with self._lock:
            self._by_object[object_id] = desc
            self._owned[desc.name] = seg

    def lookup(self, object_id: ObjectID) -> ShmDescriptor | None:
        with self._lock:
            return self._by_object.get(object_id)

    def free(self, object_id: ObjectID) -> None:
        with self._lock:
            desc = self._by_object.pop(object_id, None)
            seg = self._owned.pop(desc.name, None) if desc else None
        if isinstance(desc, ArenaDescriptor) and self._arena is not None:
            self._arena.unpin(desc.key)   # drop the seal_pinned ref
            self._arena.delete(desc.key)
            return
        if seg is not None:
            self._close_and_unlink(seg)

    def shutdown(self) -> None:
        with self._lock:
            owned = list(self._owned.values())
            self._owned.clear()
            self._by_object.clear()
        for seg in owned:
            self._close_and_unlink(seg)

    def _close_and_unlink(self, seg: shared_memory.SharedMemory) -> None:
        try:
            seg.unlink()
        except FileNotFoundError:
            pass
        try:
            seg.close()
        except BufferError:
            _defuse(seg)
            with self._lock:
                self._leaked.append(seg)
