"""Per-task/actor pip virtual environments.

Reference: python/ray/_private/runtime_env/pip.py — a virtualenv per
requirements hash, created once per node, cached, and activated for the
workers that requested it. Here the venv is built with
``--system-site-packages`` (the cluster's jax/numpy stay importable)
and activation prepends the venv's site-packages onto ``sys.path`` for
the task/actor's duration, with modules imported from it unloaded
afterwards — pool workers are shared, so the env must not leak into the
next task (same approach as working_dir/py_modules in
worker_pool._runtime_env_ctx).

Spec shapes (reference-compatible):
    runtime_env={"pip": ["pkgA", "pkgB==1.2"]}
    runtime_env={"pip": {"packages": [...],
                         "pip_install_options": ["--no-index", ...]}}
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import sys
import time

_PIP_ENV_ROOT = os.environ.get("RAY_TPU_PIP_ENV_ROOT",
                               "/tmp/ray_tpu_pip_envs")
_CREATE_TIMEOUT_S = 600.0


def normalize_pip_spec(spec) -> dict:
    if isinstance(spec, (list, tuple)):
        return {"packages": [str(p) for p in spec],
                "pip_install_options": []}
    if isinstance(spec, dict):
        return {
            "packages": [str(p) for p in spec.get("packages", [])],
            "pip_install_options": [
                str(o) for o in spec.get("pip_install_options", [])],
        }
    raise ValueError(
        f"runtime_env['pip'] must be a list of requirements or a dict "
        f"with 'packages'; got {type(spec).__name__}")


# (path, mtime_ns, size) -> content sha1: hashing a wheel is paid once
# per file VERSION, not once per task execution.
_file_hash_memo: dict[tuple, str] = {}


def _file_content_hash(path: str) -> str:
    st = os.stat(path)
    key = (os.path.abspath(path), st.st_mtime_ns, st.st_size)
    cached = _file_hash_memo.get(key)
    if cached is None:
        hasher = hashlib.sha1()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                hasher.update(chunk)
        cached = hasher.hexdigest()
        _file_hash_memo[key] = cached
        if len(_file_hash_memo) > 1024:
            _file_hash_memo.pop(next(iter(_file_hash_memo)))
    return cached


def pip_env_hash(spec) -> str:
    """Cache key: the normalized spec PLUS the content of any local
    file entries — a wheel rebuilt at the same path must produce a new
    env, never serve the stale cached one (same convention as
    runtime_env directory packaging: content-hashed per submit)."""
    norm = normalize_pip_spec(spec)
    hasher = hashlib.sha1(json.dumps(norm, sort_keys=True).encode())
    for entry in norm["packages"]:
        if os.path.isfile(entry):
            hasher.update(_file_content_hash(entry).encode())
    return hasher.hexdigest()


def _site_packages(target: str) -> str:
    lib = os.path.join(target, "lib")
    for entry in sorted(os.listdir(lib)) if os.path.isdir(lib) else []:
        cand = os.path.join(lib, entry, "site-packages")
        if os.path.isdir(cand):
            return cand
    raise FileNotFoundError(f"no site-packages under {target}")


def env_info(target: str) -> dict:
    return {
        "path": target,
        "python": os.path.join(target, "bin", "python"),
        "site_packages": _site_packages(target),
    }


def ensure_env_single_flight(target: str, create_fn,
                             timeout_s: float = _CREATE_TIMEOUT_S) -> dict:
    """Create ``target`` via ``create_fn(target)`` exactly once across
    processes (lock dir); losers wait for the winner's .complete
    marker. Shared by the pip and conda runtime-env backends."""
    marker = os.path.join(target, ".complete")
    if os.path.exists(marker):
        return env_info(target)
    os.makedirs(os.path.dirname(target), exist_ok=True)
    lock_dir = target + ".lock"
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            os.mkdir(lock_dir)
            break
        except FileExistsError:
            # Another process is creating this env: wait for it.
            if os.path.exists(marker):
                return env_info(target)
            try:
                # A creator killed without cleanup (SIGKILL/OOM) leaves
                # the lock forever; reclaim it once it is older than any
                # legitimate build could be.
                age = time.time() - os.path.getmtime(lock_dir)
                if age > timeout_s:
                    os.rmdir(lock_dir)
                    continue
            except OSError:
                pass  # lock vanished or unreadable; just retry
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"env creation lock held too long "
                    f"({lock_dir}); remove it if the creator crashed")
            time.sleep(0.25)
    # Heartbeat: refresh the lock's mtime while building so waiters'
    # stale-lock reclaim (age > timeout) never steals the lock from a
    # LIVE builder whose install legitimately runs long.
    import threading

    stop_beat = threading.Event()

    def _beat():
        while not stop_beat.wait(30.0):
            try:
                os.utime(lock_dir)
            except OSError:
                return

    beat = threading.Thread(target=_beat, daemon=True,
                            name="env-lock-heartbeat")
    beat.start()
    try:
        if os.path.exists(marker):  # winner finished while we locked
            return env_info(target)
        shutil.rmtree(target, ignore_errors=True)  # partial leftovers
        create_fn(target)
        # Validate BEFORE writing the marker: a build that "succeeded"
        # but yields no usable layout (e.g. a conda spec without
        # python → no site-packages) must fail HERE, once, with the
        # partial env removed — not loop build-then-delete on every
        # subsequent task.
        info = env_info(target)
        open(marker, "w").close()
        return info
    except BaseException:
        shutil.rmtree(target, ignore_errors=True)
        raise
    finally:
        stop_beat.set()
        try:
            os.rmdir(lock_dir)
        except OSError:
            pass  # lock dir already reclaimed


def ensure_pip_env(spec) -> dict:
    """The cached venv for ``spec`` (created on first use per node).

    -> {"path", "python", "site_packages"}.
    """
    norm = normalize_pip_spec(spec)
    key = pip_env_hash(norm)
    target = os.path.join(_PIP_ENV_ROOT, key)
    return ensure_env_single_flight(
        target, lambda t: _create_env(t, norm))


def _create_env(target: str, norm: dict) -> None:
    proc = subprocess.run(
        [sys.executable, "-m", "venv", "--system-site-packages", target],
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"venv creation failed: {proc.stderr[-2000:]}")
    if not norm["packages"]:
        return
    python = os.path.join(target, "bin", "python")
    cmd = [python, "-m", "pip", "install", "--no-input",
           "--disable-pip-version-check",
           *norm["pip_install_options"], *norm["packages"]]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"pip install failed for {norm['packages']}: "
            f"{(proc.stderr or proc.stdout)[-4000:]}")
