"""Sharded driver dispatch lanes + columnar submit records.

The driver's classic hot path pays O(tasks) object churn per flush —
a ``_SubmitRecord`` and ``TaskSpec`` per ``.remote()``, a
``_QueuedTask`` and three dict inserts per dispatcher submit, a claim
(scheduler-lock acquire) and a cluster-ledger acquire per task at
dispatch — which caps the whole driver near ~10k tasks/s however fast
execution gets. This module rebuilds that path batch-first for the
workload that dominates at scale (Podracer-style fleets of tiny
DEFAULT actor/fn tasks — arxiv 2104.06272; the Ray paper's bottom-up
scheduler exists for the same reason, arxiv 1712.05889):

- **Columnar submit records**: an eligible ``.remote()`` (frozen
  per-RemoteFunction template, scalar args, one return, no deadline /
  PG / affinity / refs) appends ONE tuple to a lock-free buffer; the
  flush builds a single :class:`ColumnarGroup` per template — parallel
  ``task_ids`` / ``return_ids`` / ``args`` columns — and registers
  lineage / TaskEvent PENDING state as per-group records expanded
  lazily only when recovery, cancellation or a state query actually
  touches a task (``spec_for``).
- **Sharded lanes**: N lane threads keyed by admission signature, each
  with its own lock domain and ready deque (locks built through the
  PR 13 ``lock_witness`` factories, classes ``dispatch_lanes.Lane`` /
  ``dispatch_lanes.DispatchLanes``). The cluster-resource ledger is
  the only shared structure and is acquired ONCE per flush
  (``ClusterState.acquire_batch`` returns a whole per-node allocation
  plan), not once per task.
- The completion fast path (get-less seals skipping future machinery)
  lives on the worker.py side (``_seal_columnar_ok``).

Disarmed (``driver_sharded_dispatch=0``), ``submit_columnar`` returns
None and every submit takes the classic ring path byte-identically;
each site costs one module-attribute branch (``SHARD_ON``).
"""

from __future__ import annotations

import collections
import threading
import time

from ray_tpu._private import lock_witness
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.task import TaskSpec

# The ONE production branch per site — disarmed, every submit falls
# back to the classic ring path (chaos.ACTIVE / perf.PERF_ON
# discipline). Armed from the driver_sharded_dispatch knob at Runtime
# init (init_from_config).
SHARD_ON: bool = True


def init_from_config() -> None:
    """Arm/disarm the sharded dispatch plane from config (Runtime init
    calls this; the envelope bench's disarmed A/B toggles the module
    attribute directly)."""
    global SHARD_ON
    SHARD_ON = bool(GLOBAL_CONFIG.driver_sharded_dispatch)


try:
    init_from_config()
except Exception:  # noqa: BLE001 — config unavailable mid-bootstrap
    pass


class ColumnarTemplate:
    """Frozen per-RemoteFunction submit template: everything a
    TaskSpec needs except the per-call ids/args, derived once at
    decoration time. Only built for columnar-ELIGIBLE functions
    (DEFAULT strategy, one return, no runtime_env, no deadline, no
    TPU demand) — everything else never reaches this path."""

    __slots__ = ("func", "name", "resources", "max_retries",
                 "retry_exceptions", "strategy", "sig")

    def __init__(self, func, name: str, resources: dict,
                 max_retries: int, retry_exceptions, strategy):
        self.func = func
        self.name = name
        self.resources = resources
        self.max_retries = max_retries
        self.retry_exceptions = retry_exceptions
        self.strategy = strategy
        # Admission signature (lane shard key): same tuple shape as
        # Dispatcher._sig so one signature's FIFO stays on one lane.
        self.sig = (tuple(sorted(resources.items())), "DEFAULT",
                    None, False)


class ColumnarGroup:
    """One flush's worth of submits for one template: parallel columns
    instead of per-task record objects. Dispatch state (``cursor``)
    advances under the owning lane's lock; ``cancelled`` holds queued
    indexes cancelled before dispatch (their slices skip them)."""

    __slots__ = ("template", "task_ids", "return_ids", "args_col",
                 "submit_ts", "by_rid", "cancelled", "cursor",
                 "requeues", "event_group", "starved_since")

    def __init__(self, template: ColumnarTemplate, task_ids: list,
                 return_ids: list, args_col: list,
                 submit_ts: "list | None" = None):
        self.template = template
        self.task_ids = task_ids
        self.return_ids = return_ids
        self.args_col = args_col
        self.submit_ts = submit_ts
        # rid -> dense index, built in one C pass (the lazy-expansion
        # key: cancel / lineage / state queries resolve through it).
        self.by_rid = dict(zip(return_ids, range(len(return_ids))))
        self.cancelled: "set[int]" = set()
        self.cursor = 0
        # idx -> invisible-requeue count (daemon-death accounting for
        # entries provably never started).
        self.requeues: "dict[int, int]" = {}
        # The GCS TaskEventGroup backing this group's PENDING state
        # (set by the flush; None when the event cap refused it).
        self.event_group = None
        # Lane-starvation stamp: first monotonic time the lane found
        # ZERO admissible capacity for this group (0.0 = not starving).
        self.starved_since = 0.0

    def __len__(self) -> int:
        return len(self.task_ids)

    def spec_for(self, idx: int) -> TaskSpec:
        """Lazily expand one columnar record into a real TaskSpec (the
        escape hatch every non-happy path takes: retries, spillback,
        recovery, need_func). The spec is equivalent to what the
        classic flush would have built for this submit."""
        t = self.template
        return TaskSpec(
            task_id=self.task_ids[idx], name=t.name, func=t.func,
            args=self.args_col[idx], kwargs={}, num_returns=1,
            resources=t.resources, max_retries=t.max_retries,
            retry_exceptions=t.retry_exceptions,
            scheduling_strategy=t.strategy,
            return_ids=[self.return_ids[idx]])


class _Lane:
    """One dispatch lane: its own lock domain + ready deque + thread.
    Only the lane thread pops; submit/cancel take the lane lock
    briefly. Capacity waits ride the shared cluster condition."""

    __slots__ = ("idx", "cond", "queue", "parked", "busy_us",
                 "dispatches", "tasks", "prev_backlog")

    def __init__(self, idx: int):
        self.idx = idx
        self.cond = lock_witness.Condition("dispatch_lanes.Lane",
                                           plain_lock=True)
        self.queue: collections.deque = collections.deque()
        self.parked = False
        # Occupancy/throughput counters (read without the lock for
        # stats — monotonic ints).
        self.busy_us = 0
        self.dispatches = 0
        self.tasks = 0
        # Accumulation-linger state: the backlog observed on the
        # previous pass (growth => the producer is mid-burst).
        self.prev_backlog = -1


class DispatchLanes:
    """N dispatch lanes draining columnar groups against the shared
    cluster ledger. ``run_slice(group, indexes, node, n_overcommit)``
    is the runtime's executor hook — called on a recycled runner
    thread with the lane already having acquired the slice's
    resources."""

    def __init__(self, cluster, run_slice, fallback=None,
                 node_filter=None, n_lanes: "int | None" = None):
        from ray_tpu._private.rpc import _ThreadRecycler

        self._cluster = cluster
        self._run_slice = run_slice
        # fallback(group, indexes): hand starved tasks to the classic
        # dispatcher (it can wait for capacity anywhere, including the
        # local node the lanes never target).
        self._fallback = fallback
        self._node_filter = node_filter
        n = n_lanes if n_lanes is not None else \
            int(GLOBAL_CONFIG.dispatch_lanes)
        self._lanes = [_Lane(i) for i in range(max(1, int(n)))]
        self._runners = _ThreadRecycler("ray_tpu-lane-slice",
                                        idle_s=30.0)
        self._shutdown = False
        # Outstanding = submitted - reached a terminal state; the
        # runtime folds it into pending_count/admission depth. Guarded
        # by its own small lock (terminal events come from runner and
        # classic-path threads).
        self._out_lock = lock_witness.Lock(
            "dispatch_lanes.DispatchLanes.outstanding")
        self._outstanding = 0
        # Concurrent slice RPCs in flight across all lanes. On a
        # single-core box every live stream's reply parts convoy the
        # GIL (per-task cost measured GROWING ~3µs per extra streaming
        # node), so the lanes keep a small number of DEEP streams
        # instead of spraying every node at once; rotation still
        # reaches all nodes over time.
        self._inflight_slices = 0
        self.max_inflight_slices = 4
        self.overcommits = 0
        self.groups_submitted = 0
        self._threads = []
        for lane in self._lanes:
            thread = threading.Thread(
                target=self._lane_loop, args=(lane,),
                name=f"ray_tpu-lane-{lane.idx}", daemon=True)
            thread.start()
            self._threads.append(thread)

    # ------------------------------------------------------------- intake

    def submit_group(self, group: ColumnarGroup) -> None:
        lane = self._lanes[hash(group.template.sig) % len(self._lanes)]
        with lane.cond:
            lane.queue.append(group)
            self.groups_submitted += 1
            if lane.parked:
                lane.cond.notify_all()
        with self._out_lock:
            self._outstanding += len(group)

    def task_done(self, n: int = 1) -> None:
        """A columnar task reached a terminal state (sealed, handed to
        the classic dispatcher, or cancelled while queued)."""
        with self._out_lock:
            self._outstanding -= n

    def outstanding(self) -> int:
        with self._out_lock:
            return self._outstanding

    def cancel(self, rid, group: ColumnarGroup) -> bool:
        """Cancel a queued columnar task (dense index via the group's
        rid map). True => the caller owns the cancel and seals the
        error; False => the task already dispatched (best-effort
        semantics, same as the classic queued-cancel)."""
        idx = group.by_rid.get(rid)
        if idx is None:
            return False
        lane = self._lanes[hash(group.template.sig) % len(self._lanes)]
        with lane.cond:
            if idx < group.cursor or idx in group.cancelled:
                return False
            group.cancelled.add(idx)
        self.task_done()
        return True

    # ------------------------------------------------------------ dispatch

    def _lane_loop(self, lane: _Lane) -> None:
        cluster = self._cluster
        while True:
            with lane.cond:
                while not lane.queue and not self._shutdown:
                    lane.parked = True
                    try:
                        lane.cond.wait(timeout=0.2)
                    finally:
                        lane.parked = False
                if self._shutdown:
                    return
                group = lane.queue[0]
                remaining = len(group) - group.cursor
                if remaining <= 0:
                    lane.queue.popleft()
                    continue
            try:
                # Columnar slices go as deep as one fused run can
                # absorb (fused_max_run_tasks) — tiny tasks amortize
                # the per-RPC cost best at full depth; the classic
                # dispatch_batch_max still floors it.
                batch_max = max(
                    int(GLOBAL_CONFIG.dispatch_batch_max),
                    int(GLOBAL_CONFIG.fused_max_run_tasks))
            except Exception:  # noqa: BLE001 — config mid-teardown
                batch_max = 256
            # The over-subscription fill budget sees the OUTSTANDING
            # population (submitted minus sealed), not just what
            # happens to sit in this lane's queue right now: the
            # pipeline drains continuously, so the queue snapshot is
            # always shallow even mid-100k-burst — sizing the fill off
            # it sprayed ~30-deep RPCs across every node where
            # 256-deep runs on a few nodes amortize far better.
            backlog = self.outstanding()
            # Accumulation linger (the ring's adaptive-linger
            # philosophy one level down): while the producer is
            # actively CHANGING the backlog, yield the core to it and
            # quantize dispatch into full-depth allocations — on a
            # single-core box tiny allocations GIL-ping-pong the
            # submit loop against the execution plane, and every RPC
            # pays its fixed cost for a shallow run. A static backlog
            # (lone submit, burst over) dispatches immediately.
            if backlog < 2 * batch_max \
                    and backlog != lane.prev_backlog \
                    and not self._shutdown:
                lane.prev_backlog = backlog
                time.sleep(0.002)
                continue
            lane.prev_backlog = backlog
            with self._out_lock:
                slots = self.max_inflight_slices \
                    - self._inflight_slices
            if slots <= 0:
                cluster.wait_for_change(0.02)
                continue
            t0 = time.monotonic()
            template = group.template
            plan = cluster.acquire_batch(
                template.resources, remaining, batch_max,
                node_filter=self._node_filter, backlog=backlog,
                # A sustained burst fills every allocation to full
                # depth; modest bursts keep the classic
                # backlog-over-nodes pacing (cancellable tail).
                fill_extra=batch_max if backlog >= 2 * batch_max
                else None,
                max_nodes=slots)
            if not plan:
                # Nothing admitted among the filtered (remote) nodes.
                # Bounded starvation: after 2s the classic dispatcher
                # takes the group — it can also wait for NEW nodes or
                # run the tasks on the local node, which lanes never
                # target.
                now = time.monotonic()
                if group.starved_since == 0.0:
                    group.starved_since = now
                elif now - group.starved_since > 2.0 \
                        and self._fallback is not None:
                    with lane.cond:
                        start = group.cursor
                        group.cursor = len(group)
                        indexes = [i for i in range(start, len(group))
                                   if i not in group.cancelled]
                    group.starved_since = 0.0
                    if indexes:
                        self._fallback(group, indexes)
                    continue
                cluster.wait_for_change(0.05)
                continue
            group.starved_since = 0.0
            for node, count, n_over in plan:
                with lane.cond:
                    start = group.cursor
                    group.cursor = start + count
                    cancelled = group.cancelled
                    if cancelled:
                        indexes = [i for i in range(start, start + count)
                                   if i not in cancelled]
                    else:
                        indexes = range(start, start + count)
                skipped = count - len(indexes)
                if skipped:
                    # Cancelled-while-queued entries already counted
                    # task_done in cancel(); give their claims back.
                    cluster.release_many(
                        node.node_id, [template.resources] * skipped)
                if n_over:
                    self.overcommits += n_over
                lane.dispatches += 1
                lane.tasks += len(indexes)
                if indexes:
                    with self._out_lock:
                        self._inflight_slices += 1
                    self._runners.submit(self._run_slice_tracked,
                                         group, indexes, node, n_over)
                else:
                    cluster.notify()
            lane.busy_us += int((time.monotonic() - t0) * 1e6)

    def _run_slice_tracked(self, group, indexes, node, n_over) -> None:
        try:
            self._run_slice(group, indexes, node, n_over)
        finally:
            with self._out_lock:
                self._inflight_slices -= 1
            # A freed stream slot is a scheduling opportunity for the
            # lanes parked on the ledger condition.
            self._cluster.notify()

    # -------------------------------------------------------------- status

    def stats(self) -> dict:
        """Lane-occupancy / throughput counters for
        execution_pipeline_stats()["dispatch"] (registered in
        DISPATCH_STAT_KEYS; the analysis counter-keys pass and
        test_doc_drift read the registry)."""
        return {
            "lanes": len(self._lanes),
            "lane_dispatches": sum(l.dispatches for l in self._lanes),
            "lane_tasks": sum(l.tasks for l in self._lanes),
            "lane_busy_us": sum(l.busy_us for l in self._lanes),
            "lane_overcommits": self.overcommits,
            "col_groups": self.groups_submitted,
            "lane_outstanding": self.outstanding(),
        }

    def queued_demands(self) -> "list[dict]":
        """Resource demands of not-yet-dispatched columnar tasks (the
        autoscaler's input, mirroring Dispatcher.pending_demands)."""
        out: list[dict] = []
        for lane in self._lanes:
            with lane.cond:
                for group in lane.queue:
                    n = len(group) - group.cursor
                    if n > 0 and group.template.resources:
                        out.extend([dict(group.template.resources)] * n)
        return out

    def shutdown(self) -> None:
        self._shutdown = True
        for lane in self._lanes:
            with lane.cond:
                lane.cond.notify_all()
