"""Memory monitor — detect host memory pressure, kill the fattest
pool worker before the OS OOM-killer takes the whole node.

Reference: python/ray/_private/memory_monitor.py +
src/ray/common/memory_monitor.h (kill a task's worker when node memory
exceeds the threshold; the task fails with OutOfMemoryError and is
retryable as a system failure).
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger("ray_tpu")


def host_memory_usage_fraction() -> float:
    """used/total from /proc/meminfo (MemAvailable-based, like the
    reference's psutil path). Returns 0.0 when unreadable."""
    try:
        info: dict[str, int] = {}
        with open("/proc/meminfo") as f:
            for line in f:
                key, _, rest = line.partition(":")
                info[key] = int(rest.strip().split()[0])  # kB
        total = info.get("MemTotal", 0)
        avail = info.get("MemAvailable", 0)
        if total <= 0:
            return 0.0
        return 1.0 - avail / total
    except OSError:
        return 0.0


# Admission-watermark memo: the overload-control plane asks "is host
# memory above the watermark?" on every admission decision; re-reading
# /proc/meminfo per task would dominate small-task admission, so the
# fraction is sampled at most once per _WATERMARK_TTL_S. Tests inject
# a fake reading via _set_usage_override.
_WATERMARK_TTL_S = 0.2
_watermark_lock = threading.Lock()
_watermark_sample = (0.0, -1e9)  # (fraction, sampled_at monotonic)
_usage_override: float | None = None
# Store-pressure axis: a registered provider reports how many of the
# host's used bytes are RESIDENT SPILLABLE STORE BYTES — pressure the
# spill tier can relieve without shedding. Tests pin the resulting
# fraction directly via _set_store_fraction_override.
_store_bytes_provider = None
_store_fraction_override: float | None = None
_host_total_kb = 0


def _set_usage_override(fraction: "float | None") -> None:
    """Test seam: pin the memory-usage fraction (None restores the
    real /proc/meminfo reader) and invalidate the memo."""
    global _usage_override, _watermark_sample
    with _watermark_lock:
        _usage_override = fraction
        _watermark_sample = (0.0, -1e9)


def set_store_bytes_provider(fn) -> None:
    """Register the () -> resident-spillable-store-bytes callable the
    pressure classifier subtracts from host usage (the runtime/daemon
    installs its store's resident-bytes reader here)."""
    global _store_bytes_provider
    _store_bytes_provider = fn


def _set_store_fraction_override(fraction: "float | None") -> None:
    """Test seam for the store axis: pin the store-bytes share of
    host memory directly (None restores the provider path)."""
    global _store_fraction_override
    _store_fraction_override = fraction


def _store_fraction() -> float:
    """Resident spillable store bytes as a fraction of host memory."""
    if _store_fraction_override is not None:
        return _store_fraction_override
    provider = _store_bytes_provider
    if provider is None:
        return 0.0
    global _host_total_kb
    if _host_total_kb <= 0:
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal"):
                        _host_total_kb = int(line.split()[1])
                        break
        except OSError:
            return 0.0
    if _host_total_kb <= 0:
        return 0.0
    try:
        return float(provider()) / (_host_total_kb * 1024.0)
    except Exception:  # noqa: BLE001 — classification must never raise
        return 0.0


def memory_watermark_exceeded(watermark: float) -> bool:
    """True when host memory usage is at/above ``watermark`` (a
    fraction; <= 0 disables). Memoized for _WATERMARK_TTL_S."""
    if watermark <= 0.0:
        return False
    import time

    global _watermark_sample
    now = time.monotonic()
    with _watermark_lock:
        frac, at = _watermark_sample
        if now - at <= _WATERMARK_TTL_S:
            return frac >= watermark
        frac = (_usage_override if _usage_override is not None
                else host_memory_usage_fraction())
        _watermark_sample = (frac, now)
        return frac >= watermark


def memory_pressure_kind(watermark: float) -> "str | None":
    """Classify admission memory pressure on TWO axes instead of
    conflating them (the PR-7 watermark shed treated every byte the
    same): ``None`` = under the watermark, ``"store"`` = over it but
    evicting resident store bytes would bring the host back under
    (recoverable — trigger spilling, admit), ``"host"`` = true host
    RSS pressure spilling cannot relieve (shed).

    Both axes are unit-testable via _set_usage_override (host) and
    _set_store_fraction_override (store)."""
    if watermark <= 0.0 or not memory_watermark_exceeded(watermark):
        return None
    with _watermark_lock:
        host_frac = _watermark_sample[0]
    if host_frac - _store_fraction() < watermark:
        return "store"
    return "host"


def process_rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            pages = int(f.read().split()[1])
        import resource

        return pages * (resource.getpagesize())
    except (OSError, IndexError, ValueError):
        return 0


class MemoryMonitor:
    """Polls host memory; above the threshold, kills the pool worker
    with the largest RSS (its in-flight task fails as a system failure
    and is retryable, matching the reference's OOM-kill policy)."""

    def __init__(self, runtime, threshold: float = 0.95,
                 period_s: float = 1.0):
        self.runtime = runtime
        self.threshold = threshold
        self.period_s = period_s
        self.num_kills = 0
        # Pids this monitor killed: their WorkerCrashedErrors are
        # OOM failures, retried beyond the task's own max_retries
        # (reference: OOM kills get their own retry budget). Bounded +
        # consumed on attribution so an OS-recycled pid cannot
        # misclassify an unrelated crash hours later.
        self.killed_pids: set[int] = set()
        self._kill_order: list[int] = []
        self._shutdown = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="memory-monitor")

    def start(self) -> "MemoryMonitor":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._shutdown.wait(self.period_s):
            self.check_once()

    def consume_attribution(self, pid: int) -> None:
        """Forget a kill after its final retry attempt (keeps a
        recycled pid from reclassifying a future unrelated crash)."""
        self.killed_pids.discard(pid)
        try:
            self._kill_order.remove(pid)
        except ValueError:
            pass

    def check_once(self) -> int | None:
        """One pressure check; returns the killed pid (or None)."""
        usage = host_memory_usage_fraction()
        if usage <= self.threshold:
            return None
        pool = getattr(self.runtime, "worker_pool", None)
        if pool is None:
            logger.warning(
                "memory pressure: host at %.0f%% (threshold %.0f%%) — "
                "no worker pool to reclaim from", usage * 100,
                self.threshold * 100)
            return None
        victim = self._largest_worker(pool)
        if victim is None:
            return None
        pid = victim.proc.pid
        logger.warning(
            "memory pressure: host at %.0f%% — killing pool worker "
            "pid=%s rss=%.0fMB (its task fails with a retryable "
            "system error)", usage * 100, pid,
            process_rss_bytes(pid) / 1e6)
        self.killed_pids.add(pid)
        self._kill_order.append(pid)
        while len(self._kill_order) > 64:
            self.killed_pids.discard(self._kill_order.pop(0))
        try:
            victim.proc.kill()
        except OSError:
            return None
        self.num_kills += 1
        return pid

    @staticmethod
    def _largest_worker(pool):
        # Idle AND busy workers are candidates: killing a busy worker
        # fails its task with a retryable system error, which the
        # reference prefers over the OS OOM-killer taking the node.
        alive = pool.live_workers()
        if not alive:
            return None
        return max(alive, key=lambda w: process_rss_bytes(w.proc.pid))

    def stop(self) -> None:
        self._shutdown.set()
