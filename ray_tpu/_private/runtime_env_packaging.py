"""Runtime-env directory packaging: ship working_dir / py_modules to
every node that runs the task.

Reference: python/ray/_private/runtime_env/packaging.py — local dirs
become content-hashed zip packages (gcs://_ray_pkg_<hash>.zip) uploaded
once, downloaded + extracted once per node, cached by hash. Here the
driver's object export server is the distribution plane (the same
chunked fetch_object path task arguments ride), so packages flow
driver → node exactly once regardless of task count.

Wire format: a runtime_env entry that named a local directory becomes
``{"__pkg__": [hash_hex, export_addr]}``; worker-side resolution
downloads (or reuses the cache) and substitutes the extracted path.
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile

_CACHE_ROOT = os.environ.get("RAY_TPU_PKG_CACHE",
                             "/tmp/ray_tpu_pkg_cache")
_EXCLUDE_DIRS = {"__pycache__", ".git"}
_MAX_PACKAGE_BYTES = 512 * 1024 * 1024


def hash_directory(path: str) -> str:
    """Content hash of a directory (same walk/ordering as
    package_directory, no zipping) — cheap enough to run per submit so
    edited sources re-ship instead of serving a stale cache."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env path {path!r} is not a directory")
    hasher = hashlib.sha1()
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for name in sorted(files):
            if name.endswith(".pyc"):
                continue
            full = os.path.join(root, name)
            hasher.update(os.path.relpath(full, path).encode())
            with open(full, "rb") as f:
                hasher.update(f.read())
    return hasher.hexdigest()


def package_directory(path: str) -> tuple[str, bytes]:
    """Deterministic zip of a directory -> (content_hash_hex, bytes).

    Deterministic (sorted entries, fixed timestamps) so the hash is
    stable across runs and caches hit."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env path {path!r} is not a directory")
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for name in sorted(files):
            if name.endswith(".pyc"):
                continue
            full = os.path.join(root, name)
            entries.append((os.path.relpath(full, path), full))
    buf = io.BytesIO()
    hasher = hashlib.sha1()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in entries:
            with open(full, "rb") as f:
                data = f.read()
            hasher.update(rel.encode())
            hasher.update(data)
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            zf.writestr(info, data)
    blob = buf.getvalue()
    if len(blob) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package for {path!r} is {len(blob)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES}); exclude build artifacts")
    return hasher.hexdigest(), blob


def ensure_package_local(hash_hex: str, export_addr: str,
                         member: str | None = None) -> str:
    """Extracted package directory for ``hash_hex``, downloading from
    the owner's export server on first use (per-node cache).

    ``member``: for py_modules the importable directory must keep its
    NAME, so contents extract under ``<cache>/<hash>/<member>/`` and
    that inner path is returned; working_dir packages extract flat."""
    # Cache key includes the member name: the same content extracts to
    # different layouts for working_dir vs py_modules use.
    target = os.path.join(
        _CACHE_ROOT, hash_hex + (f"-{member}" if member else ""))
    inner = os.path.join(target, member) if member else target
    marker = os.path.join(target, ".complete")
    if os.path.exists(marker):
        return inner
    from ray_tpu._private.node_executor import fetch_blob
    from ray_tpu._private.rpc import RpcClient

    client = RpcClient(export_addr, timeout_s=120.0)
    try:
        blob = fetch_blob(client, bytes.fromhex(hash_hex))
    finally:
        client.close()
    tmp = target + f".tmp.{os.getpid()}"
    extract_to = os.path.join(tmp, member) if member else tmp
    os.makedirs(extract_to, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(extract_to)
    open(os.path.join(tmp, ".complete"), "w").close()
    try:
        os.rename(tmp, target)
    except OSError:
        # Concurrent extraction won the rename; use the winner.
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return inner


def ensure_file_local(hash_hex: str, export_addr: str,
                      basename: str) -> str:
    """A single packaged file (e.g. a wheel in a pip spec), downloaded
    from the owner's export server on first use (per-node cache). The
    cache path embeds the content hash, so changed content lands at a
    new path."""
    target_dir = os.path.join(_CACHE_ROOT, f"file-{hash_hex}")
    target = os.path.join(target_dir, basename)
    if os.path.exists(target):
        return target
    from ray_tpu._private.node_executor import fetch_blob
    from ray_tpu._private.rpc import RpcClient

    client = RpcClient(export_addr, timeout_s=120.0)
    try:
        blob = fetch_blob(client, bytes.fromhex(hash_hex))
    finally:
        client.close()
    os.makedirs(target_dir, exist_ok=True)
    tmp = target + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    try:
        os.rename(tmp, target)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass  # loser's tmp already swept
    return target


def resolve_runtime_env(renv: dict | None) -> dict | None:
    """Worker-side: replace ``{"__pkg__": [hash, addr, member]}`` and
    ``{"__pip_file__": [hash, addr, name]}`` markers with local
    paths."""
    if not renv:
        return renv

    def resolve(value):
        if isinstance(value, dict) and "__pkg__" in value:
            hash_hex, addr, member = value["__pkg__"]
            return ensure_package_local(hash_hex, addr, member)
        return value

    out = dict(renv)
    if "working_dir" in out:
        out["working_dir"] = resolve(out["working_dir"])
    if out.get("py_modules"):
        out["py_modules"] = [resolve(m) for m in out["py_modules"]]
    pip_spec = out.get("pip")
    if isinstance(pip_spec, dict) and pip_spec.get("packages"):
        packages = []
        for entry in pip_spec["packages"]:
            if isinstance(entry, dict) and "__pip_file__" in entry:
                packages.append(ensure_file_local(*entry["__pip_file__"]))
            else:
                packages.append(entry)
        out["pip"] = {**pip_spec, "packages": packages}
    return out
