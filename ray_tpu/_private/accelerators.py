"""Accelerator detection — TPU as a first-class scheduler resource.

Reference: python/ray/_private/accelerators/tpu.py (398 LoC) detects TPU
chips via GKE env vars / GCE metadata and advertises a pod-slice head
resource ``TPU-{pod_type}-head`` so one task can claim a whole slice
(tpu.py:382). Here detection is layered: GKE/GCE environment metadata
first (cheap, no jax init — reference tpu.py:14-44), then JAX-native
device enumeration; topology labels come from whichever layer answered.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("ray_tpu")

# Valid per-host chip counts (reference: tpu.py:13) — a metadata value
# outside this set means a misconfigured node, not more chips.
_VALID_CHIPS_PER_HOST = (1, 2, 4, 8)

_GCE_METADATA_URL = ("http://metadata.google.internal/computeMetadata"
                     "/v1/instance/attributes/")


def _on_gce() -> bool:
    """Cheap LOCAL check for Google Compute Engine (DMI product name) —
    off-cloud hosts must never touch metadata DNS (getaddrinfo is not
    bounded by urlopen's timeout and can stall node startup)."""
    try:
        with open("/sys/class/dmi/id/product_name") as f:
            return "Google" in f.read()
    except OSError:
        return False


def _gce_metadata(key: str, timeout_s: float = 0.5) -> str | None:
    """GCE instance-attribute lookup (reference: tpu.py GCE branch)."""
    if not _on_gce():
        return None
    import urllib.request

    try:
        req = urllib.request.Request(
            _GCE_METADATA_URL + key,
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.read().decode()
    except Exception:  # noqa: BLE001 — no egress / metadata absent
        return None


def detect_tpu_topology() -> dict | None:
    """GKE/GCE TPU topology from environment metadata, or None.

    GKE injects TPU_ACCELERATOR_TYPE (e.g. "v5litepod-16") and
    TPU_WORKER_ID / TPU_WORKER_HOSTNAMES (reference: tpu.py:14-28);
    plain GCE TPU-VMs expose the same through the metadata server.
    Returns {"accelerator_type", "worker_id", "num_workers",
    "chips_per_host"}.
    """
    accel = os.environ.get("TPU_ACCELERATOR_TYPE") \
        or _gce_metadata("accelerator-type")
    if not accel:
        return None
    raw_worker = os.environ.get("TPU_WORKER_ID") \
        or _gce_metadata("agent-worker-number") or "0"
    try:
        worker_id = int(raw_worker.strip())
    except ValueError:
        # Corrupt metadata (captive portal, proxy page): assume worker
        # 0 rather than failing the whole node's resource detection.
        worker_id = 0
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES") \
        or _gce_metadata("worker-network-endpoints") or ""
    num_workers = max(1, len([h for h in hostnames.split(",") if h]))
    # Per-host chip count from TPU_CHIPS_PER_HOST_BOUNDS ("2,2,1" =>
    # 4 chips — reference: tpu.py:44), else from the accelerator type.
    chips = None
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
    if bounds:
        try:
            n = 1
            for part in bounds.split(","):
                n *= int(part)
            chips = n
        except ValueError:
            chips = None
    if chips is None:
        try:
            total = int(accel.rsplit("-", 1)[1])
            # v2/v3/v4/v5p accelerator-type suffixes count TENSORCORES
            # (2 per chip); v5e (v5litepod) and v6e suffixes count
            # chips (reference: tpu.py's per-generation tables).
            gen = accel.split("-")[0].lower()
            if gen in ("v2", "v3", "v4", "v5p"):
                total = max(1, total // 2)
            chips = max(1, total // num_workers)
        except (ValueError, IndexError):
            chips = 4
    if chips not in _VALID_CHIPS_PER_HOST:
        logger.warning(
            "TPU metadata reports %s chips/host (valid: %s); clamping",
            chips, _VALID_CHIPS_PER_HOST)
        chips = min(_VALID_CHIPS_PER_HOST,
                    key=lambda v: abs(v - chips))
    return {
        "accelerator_type": accel,
        "worker_id": int(worker_id),
        "num_workers": num_workers,
        "chips_per_host": chips,
    }


def detect_resources() -> dict[str, float]:
    """Detect local accelerator resources without initializing heavy state."""
    resources: dict[str, float] = {}
    override = os.environ.get("RAY_TPU_NUM_TPU_CHIPS")
    if override is not None:
        count = float(override)
        if count > 0:
            resources["TPU"] = count
        return resources
    if os.environ.get("RAY_TPU_SKIP_TPU_DETECTION"):
        return resources
    # Layer 1: GKE/GCE metadata — no jax init, and it knows the SLICE
    # shape, not just the local chips (reference: tpu.py:14-44, :382).
    topo = detect_tpu_topology()
    if topo is not None:
        resources["TPU"] = float(topo["chips_per_host"])
        if topo["worker_id"] == 0:
            # Pod-slice gang resource on worker 0 ONLY: exactly one
            # task per slice can claim the whole gang (tpu.py:363-382).
            resources[f"TPU-{topo['accelerator_type']}-head"] = 1.0
        return resources
    # Layer 2: JAX device enumeration (single-host / dev boxes).
    try:
        import jax

        tpu_devices = [d for d in jax.devices() if d.platform == "tpu"]
        if tpu_devices:
            resources["TPU"] = float(len(tpu_devices))
            kind = tpu_devices[0].device_kind.replace(" ", "-")
            # Pod-slice gang resource, mirroring TPU-{pod_type}-head
            # (reference: tpu.py:382): exactly one per host group.
            resources[f"TPU-{kind}-head"] = 1.0
    except Exception:  # pragma: no cover — no jax / no TPU is fine
        pass
    return resources


def visible_chip_env(chip_ids: list[int]) -> dict[str, str]:
    """Env isolating a worker to specific chips (reference: tpu.py:30
    TPU_VISIBLE_CHIPS)."""
    return {
        "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chip_ids),
        "TPU_CHIPS_PER_PROCESS_BOUNDS": "1,1,1",
        "TPU_PROCESS_BOUNDS": "1,1,1",
    }
