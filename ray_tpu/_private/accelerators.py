"""Accelerator detection — TPU as a first-class scheduler resource.

Reference: python/ray/_private/accelerators/tpu.py (398 LoC) detects TPU
chips via GKE env vars / GCE metadata and advertises a pod-slice head
resource ``TPU-{pod_type}-head`` so one task can claim a whole slice
(tpu.py:382). Here TPU detection is JAX-native: if jax sees TPU devices we
advertise them; topology labels come from the device kind.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("ray_tpu")


def detect_resources() -> dict[str, float]:
    """Detect local accelerator resources without initializing heavy state."""
    resources: dict[str, float] = {}
    override = os.environ.get("RAY_TPU_NUM_TPU_CHIPS")
    if override is not None:
        count = float(override)
        if count > 0:
            resources["TPU"] = count
        return resources
    if os.environ.get("RAY_TPU_SKIP_TPU_DETECTION"):
        return resources
    try:
        import jax

        tpu_devices = [d for d in jax.devices() if d.platform == "tpu"]
        if tpu_devices:
            resources["TPU"] = float(len(tpu_devices))
            kind = tpu_devices[0].device_kind.replace(" ", "-")
            # Pod-slice gang resource, mirroring TPU-{pod_type}-head
            # (reference: tpu.py:382): exactly one per host group.
            resources[f"TPU-{kind}-head"] = 1.0
    except Exception:  # pragma: no cover — no jax / no TPU is fine
        pass
    return resources


def visible_chip_env(chip_ids: list[int]) -> dict[str, str]:
    """Env isolating a worker to specific chips (reference: tpu.py:30
    TPU_VISIBLE_CHIPS)."""
    return {
        "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chip_ids),
        "TPU_CHIPS_PER_PROCESS_BOUNDS": "1,1,1",
        "TPU_PROCESS_BOUNDS": "1,1,1",
    }
