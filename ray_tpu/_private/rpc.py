"""RPC layer — length-prefixed pickle messages over TCP.

TPU-native analogue of the reference's gRPC plumbing
(src/ray/rpc/grpc_server.h, grpc_client.h): every cross-process control
message in the reference is protobuf-over-gRPC; here it is
pickle-over-TCP with an 8-byte length prefix. Pickle is acceptable for
the same reason the reference ships cloudpickle blobs inside its
protobufs: cluster links are trusted (same security model).

Server: thread-per-connection, sequential dispatch per connection (the
reference's gRPC servers are also ordered per stream). Client: one
socket, calls serialized under a lock, transparent reconnect on a dead
socket (e.g. head restarted).
"""

from __future__ import annotations

import pickle
import select
import socket
import struct
import threading
import traceback
from typing import Any, Callable

_LEN = struct.Struct(">Q")
MAX_FRAME = 1 << 31  # 2GB sanity bound


class RpcError(ConnectionError):
    """Transport-level failure (peer unreachable / connection lost)."""


class RpcMethodError(Exception):
    """The remote method raised; carries the remote traceback."""

    def __init__(self, cause: BaseException, remote_tb: str):
        super().__init__(f"{type(cause).__name__}: {cause}")
        self.cause = cause
        self.remote_tb = remote_tb


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise RpcError("connection closed by peer")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return _recv_exact(sock, length)


class RpcServer:
    """Serves registered callables; ``register_object`` exposes every
    public method of an object (the gRPC service pattern)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        self._methods: dict[str, Callable] = {}
        self._shutdown = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, name: str, fn: Callable) -> None:
        self._methods[name] = fn

    def register_object(self, obj: Any, prefix: str = "") -> None:
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self._methods[prefix + name] = fn

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rpc-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.5)
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rpc-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._shutdown.is_set():
                try:
                    frame = _recv_frame(conn)
                except RpcError:
                    return
                seq, method, args, kwargs = pickle.loads(frame)
                try:
                    fn = self._methods[method]
                except KeyError:
                    reply = (seq, "err", (KeyError(f"no method {method}"),
                                          ""))
                else:
                    try:
                        reply = (seq, "ok", fn(*args, **kwargs))
                    except BaseException as exc:  # noqa: BLE001
                        tb = traceback.format_exc()
                        try:
                            pickle.dumps(exc)
                        except Exception:
                            exc = RuntimeError(
                                f"{type(exc).__name__}: {exc}")
                        reply = (seq, "err", (exc, tb))
                try:
                    _send_frame(conn, pickle.dumps(reply))
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass

    def stop(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


class RpcClient:
    """One connection; calls are serialized (seq-matched replies)."""

    def __init__(self, address: str, timeout_s: float = 30.0,
                 connect_timeout_s: float | None = None):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.address = f"{self._addr[0]}:{self._addr[1]}"
        self._timeout = timeout_s
        # Long read timeouts (blocking task executions) must not make
        # CONNECTING to a dead host block equally long.
        self._connect_timeout = (connect_timeout_s
                                 if connect_timeout_s is not None
                                 else min(timeout_s, 10.0))
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._seq = 0

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            self._addr, timeout=self._connect_timeout)
        sock.settimeout(self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    @staticmethod
    def _stale(sock: socket.socket) -> bool:
        """A pooled idle socket with a pending EOF/RST shows readable
        (no reply is outstanding, so ANY readability means the peer
        closed). Detecting this before send keeps the common
        server-restart case retriable without double-execution risk."""
        try:
            readable, _, _ = select.select([sock], [], [], 0)
            return bool(readable)
        except (OSError, ValueError):
            return True

    def call(self, method: str, *args, **kwargs) -> Any:
        with self._lock:
            self._seq += 1
            seq = self._seq
            request = pickle.dumps((seq, method, args, kwargs))
            last_exc: Exception | None = None
            for attempt in range(2):  # one transparent reconnect
                # Retry is safe ONLY while the server cannot have executed
                # the request: before the full frame was handed to the
                # kernel. Once sendall returns, a lost reply may mean the
                # method ran — surface RpcError instead of re-sending
                # (non-idempotent methods would double-execute).
                sent = False
                try:
                    if self._sock is not None and self._stale(self._sock):
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    if self._sock is None:
                        self._sock = self._connect()
                    _send_frame(self._sock, request)
                    sent = True
                    rseq, status, payload = pickle.loads(
                        _recv_frame(self._sock))
                    if rseq != seq:
                        raise RpcError(
                            f"out-of-order reply: {rseq} != {seq}")
                    break
                except (OSError, RpcError, EOFError) as exc:
                    last_exc = exc
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    if sent:
                        raise RpcError(
                            f"rpc {method} to {self.address} failed after "
                            f"send (may have executed): {exc}") from exc
            else:
                raise RpcError(
                    f"rpc to {self.address} failed: {last_exc}") \
                    from last_exc
        if status == "err":
            exc, tb = payload
            raise RpcMethodError(exc, tb)
        return payload

    def ping(self) -> bool:
        try:
            return self.call("ping") == "pong"
        except (RpcError, RpcMethodError):
            return False

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
