"""RPC layer — length-prefixed pickle messages over TCP.

TPU-native analogue of the reference's gRPC plumbing
(src/ray/rpc/grpc_server.h, grpc_client.h): every cross-process control
message in the reference is protobuf-over-gRPC; here it is
pickle-over-TCP with an 8-byte length prefix. Pickle is acceptable for
the same reason the reference ships cloudpickle blobs inside its
protobufs: cluster links are trusted (same security model).

Server: thread-per-connection; registered-concurrent methods dispatch
off the connection loop (recycled threads / a pooled executor) with
out-of-order replies, so one connection carries many interleaved calls
(the gRPC completion-queue shape). Clients:

- ``MuxRpcClient`` — pipelined: seq-tagged frames, a reader thread,
  per-call futures (``call_async``), and per-destination coalescing of
  chatty control calls into ``__batch__`` frames.
- ``RpcClient`` — one call at a time under a lock with a transparent
  reconnect; kept for short control probes and legacy paths.
"""

from __future__ import annotations

import pickle
import select
import socket
import struct
import threading

from ray_tpu._private import lock_witness
import time
import traceback
from typing import Any, Callable

from ray_tpu._private import chaos

_LEN = struct.Struct(">Q")
MAX_FRAME = 1 << 31  # 2GB sanity bound


class RpcError(ConnectionError):
    """Transport-level failure (peer unreachable / connection lost).

    ``maybe_executed`` classifies the failure for retry policy: False
    means the request provably never reached the server (connect
    refused, client closed, stale-socket detection) — ALWAYS safe to
    retry; True means the frame was (or may have been) handed to the
    kernel before the failure — only IDEMPOTENT callers may retry, a
    non-idempotent submit riding a blind retry would double-execute."""

    def __init__(self, *args, maybe_executed: bool = False):
        super().__init__(*args)
        self.maybe_executed = maybe_executed


class TailPayload:
    """Reply wrapper for bulk data: ``head`` is pickled normally,
    ``tail`` (any buffer) is appended RAW after the pickle inside the
    same frame — the chunk bytes are never copied through pickle on
    either side (the zero-copy serve path for fetch_object). The
    caller receives ``(head, tail_view)`` where tail_view is a
    memoryview into the received frame buffer."""

    __slots__ = ("head", "tail")

    def __init__(self, head: Any, tail):
        self.head = head
        self.tail = tail


class _StreamEnd:
    """Sentinel closing a streaming call's parts queue (the final
    reply or a connection failure has resolved the slot)."""


_STREAM_END = _StreamEnd()


class RpcMethodError(Exception):
    """The remote method raised; carries the remote traceback."""

    def __init__(self, cause: BaseException, remote_tb: str):
        super().__init__(f"{type(cause).__name__}: {cause}")
        self.cause = cause
        self.remote_tb = remote_tb

    def __reduce__(self):
        # Exception's default reduce re-calls __init__ with args=(the
        # formatted message,) — one argument short; an RpcMethodError
        # crossing ANOTHER pickle boundary (e.g. stored as a task error
        # and shipped to a different process) must round-trip.
        return (RpcMethodError, (self.cause, self.remote_tb))


def classify_rpc_failure(exc: BaseException) -> str:
    """Retry classification for a failed RPC:

    - ``"retryable"``: the request never reached the server — safe for
      ANY caller to retry.
    - ``"maybe_executed"``: the request was (or may have been) sent;
      only idempotent callers retry, non-idempotent submits must
      surface the failure (double-execution risk).
    - ``"poisoned"``: the remote method itself raised — retrying
      re-raises; the failure is the answer.
    """
    if isinstance(exc, RpcMethodError):
        return "poisoned"
    if isinstance(exc, RpcError):
        return "maybe_executed" if exc.maybe_executed else "retryable"
    # Bare socket errors surface from connect paths only (everything
    # post-send is wrapped into RpcError by the clients).
    return "retryable" if isinstance(exc, OSError) else "poisoned"


# Process-wide transport fault counters, surfaced through
# executor_stats()["faults"] / Runtime.fault_stats().
_FAULTS_LOCK = lock_witness.Lock("rpc.FAULTS")
_RPC_RETRIES = 0


class _Breaker:
    """Per-destination circuit breaker state (see call_with_retry).

    closed -> open after ``rpc_breaker_failures`` CONSECUTIVE logical-
    call failures; open -> one half-open probe after
    ``rpc_breaker_reset_s``; probe success closes, probe failure
    re-opens. All transitions under the module breaker lock."""

    __slots__ = ("failures", "open", "opened_at", "probing")

    def __init__(self):
        self.failures = 0
        self.open = False
        self.opened_at = 0.0
        self.probing = False


_BREAKERS_LOCK = lock_witness.Lock("rpc.BREAKERS")
_BREAKERS: dict[str, _Breaker] = {}
_BREAKER_OPENS = 0  # monotonic: total closed->open transitions


def _breaker_knobs() -> tuple[int, float]:
    try:
        from ray_tpu._private.config import GLOBAL_CONFIG

        return (int(GLOBAL_CONFIG.rpc_breaker_failures),
                float(GLOBAL_CONFIG.rpc_breaker_reset_s))
    except Exception:  # noqa: BLE001 — config gone mid-teardown
        return 0, 5.0


def breaker_allow(dest: str) -> bool:
    """May a logical call to ``dest`` hit the wire right now? An open
    breaker admits exactly ONE half-open probe per reset interval."""
    threshold, reset_s = _breaker_knobs()
    if threshold <= 0:
        return True
    with _BREAKERS_LOCK:
        breaker = _BREAKERS.get(dest)
        if breaker is None or not breaker.open:
            return True
        if breaker.probing:
            return False
        if time.monotonic() - breaker.opened_at >= reset_s:
            breaker.probing = True  # this caller is the probe
            return True
        return False


def breaker_record(dest: str, ok: bool) -> None:
    """Outcome of one LOGICAL call to ``dest`` (a call_with_retry
    invocation reports at most one failure, however many attempts it
    burned — retries of the same call must not multi-count)."""
    global _BREAKER_OPENS
    threshold, _ = _breaker_knobs()
    if threshold <= 0:
        return
    with _BREAKERS_LOCK:
        breaker = _BREAKERS.get(dest)
        if ok:
            if breaker is not None:
                breaker.failures = 0
                breaker.open = False
                breaker.probing = False
            return
        if breaker is None:
            breaker = _BREAKERS[dest] = _Breaker()
        was_open = breaker.open
        breaker.failures += 1
        breaker.probing = False
        if breaker.failures >= threshold or was_open:
            # Reaching the threshold opens; a failed half-open probe
            # re-arms the timer without re-counting an open.
            if not was_open:
                _BREAKER_OPENS += 1
                # Breaker transitions are exactly the kind of rare,
                # load-bearing event a post-mortem ring should carry.
                from ray_tpu._private import flight_recorder

                flight_recorder.record("breaker.open", dest)
            breaker.open = True
            breaker.opened_at = time.monotonic()


def breaker_stats() -> dict:
    """Breaker state for fault_stats()/metrics: total opens plus the
    destinations currently open."""
    with _BREAKERS_LOCK:
        return {
            "opens": _BREAKER_OPENS,
            "open_now": sorted(d for d, b in _BREAKERS.items()
                               if b.open),
        }


def reset_breakers() -> None:
    """Test seam: drop all breaker state and the opens counter."""
    global _BREAKER_OPENS
    with _BREAKERS_LOCK:
        _BREAKERS.clear()
        _BREAKER_OPENS = 0


def _record_retry() -> None:
    global _RPC_RETRIES
    with _FAULTS_LOCK:
        _RPC_RETRIES += 1
    # Fault counters double as timeline pins: a retried control call
    # shows up at its wall-clock position next to the stage slices it
    # delayed. One lazy-import branch; retries are rare by definition.
    from ray_tpu.util import tracing

    if tracing.TRACE_ON:
        import os as _os

        tag = _os.environ.get("RAY_TPU_NODE_TAG")
        if tag:
            tracing.buffer_instant("fault:rpc_retry", f"node:{tag[:8]}")
        else:
            tracing.instant("fault:rpc_retry")


def rpc_retry_count() -> int:
    with _FAULTS_LOCK:
        return _RPC_RETRIES


def overload_retry_after(exc: BaseException) -> "float | None":
    """The typed backpressure hint carried by a remote
    ``SystemOverloadedError`` (clamped to the local backoff cap so a
    long server-side stall never wedges the caller), or None when
    ``exc`` is not an overload shed."""
    cause = getattr(exc, "cause", None)
    from ray_tpu.exceptions import SystemOverloadedError

    if isinstance(cause, SystemOverloadedError):
        return min(
            max(float(getattr(cause, "retry_after_s", 0.1)), 0.05),
            2.0)
    return None


def call_with_retry(call: Callable, method: str, *args,
                    attempts: int | None = None,
                    base_delay_s: float | None = None,
                    deadline_s: float | None = None,
                    **kwargs) -> Any:
    """Shared retry/backoff/deadline policy for IDEMPOTENT
    control-plane calls (heartbeats, fetch_plan, GCS reads).

    MuxRpcClient documents "the caller owns the retry policy"; this is
    the one policy idempotent callers share, so each site stops owning
    nothing. Maybe-executed failures ARE retried here — by contract
    the wrapped method must be idempotent; never route task submits or
    actor creations through this (classify_rpc_failure + surfacing is
    their path).

    A per-destination circuit breaker rides the policy: a destination
    failing ``rpc_breaker_failures`` consecutive LOGICAL calls opens,
    and further calls fail fast with a retryable RpcError instead of
    burning whole attempt/backoff budgets against a sick node. Breaker
    accounting uses classify_rpc_failure: "poisoned" (the remote
    method raised — the node is demonstrably alive) counts as success,
    while retryable AND maybe_executed transport failures (including
    bare OSErrors off connect paths) count as failure — once per
    logical call, however many attempts it burned."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    if attempts is None:
        attempts = max(1, int(GLOBAL_CONFIG.rpc_retry_attempts))
    if base_delay_s is None:
        base_delay_s = float(GLOBAL_CONFIG.rpc_retry_base_ms) / 1000.0
    if deadline_s is None:
        deadline_s = float(GLOBAL_CONFIG.rpc_retry_deadline_s)
    # The destination is the bound client's address (MuxRpcClient /
    # RpcClient .call); free functions without one skip the breaker.
    dest = getattr(getattr(call, "__self__", None), "address", None)
    counted = False  # breaker: at most one failure per logical call
    deadline = time.monotonic() + deadline_s
    for attempt in range(attempts):
        if dest is not None and not breaker_allow(dest):
            raise RpcError(
                f"rpc {method} to {dest} rejected: circuit breaker "
                f"open (destination failing consecutively)")
        try:
            result = call(method, *args, **kwargs)
        except RpcMethodError as exc:
            # "poisoned": the remote raised — the node is alive and
            # answering. Propagate (retrying re-raises) and close the
            # failure streak.
            if dest is not None:
                breaker_record(dest, True)
            retry_after = overload_retry_after(exc)
            if retry_after is not None and attempt + 1 < attempts \
                    and time.monotonic() + retry_after < deadline:
                # Typed shed (SystemOverloadedError) from a degraded
                # remote — e.g. a stalled GCS shard's write queue at
                # cap: an idempotent call honors the server's bounded
                # retry_after_s hint instead of failing a call the
                # remote explicitly asked to see again.
                _record_retry()
                time.sleep(retry_after)
                continue
            raise
        except (RpcError, OSError) as exc:
            if dest is not None and not counted \
                    and classify_rpc_failure(exc) != "poisoned":
                counted = True
                breaker_record(dest, False)
            if attempt + 1 >= attempts or time.monotonic() >= deadline:
                raise
            _record_retry()
            time.sleep(min(base_delay_s * (2 ** attempt), 2.0))
        else:
            if dest is not None:
                breaker_record(dest, True)
            return result
    raise RpcError(f"rpc {method} retry loop exhausted")  # unreachable


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    if len(payload) >= (1 << 16):
        # Large frames (chunk replies): two sendalls beat concatenating
        # header+payload into a fresh multi-MB buffer per frame.
        sock.sendall(_LEN.pack(len(payload)))
        sock.sendall(payload)
    else:
        sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise RpcError("connection closed by peer")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    if length <= (1 << 16):
        return _recv_exact(sock, length)
    # Large frames: receive straight into one preallocated buffer —
    # no per-recv chunk list and no final join copy.
    buf = bytearray(length)
    view = memoryview(buf)
    off = 0
    while off < length:
        # No artificial cap: recv_into fills whatever the kernel has
        # ready — fewer syscalls/GIL trips per large frame.
        got = sock.recv_into(view[off:])
        if not got:
            raise RpcError("connection closed by peer")
        off += got
    return buf  # bytes-like; every caller feeds it to pickle.loads


class _Recycled:
    """One reusable dispatch thread; parks in its pool's LIFO free list
    between jobs and expires after an idle timeout."""

    __slots__ = ("_pool", "_event", "_job", "_thread")

    def __init__(self, pool: "_ThreadRecycler"):
        self._pool = pool
        self._event = threading.Event()
        self._job = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=pool.name)
        self._thread.start()

    def run(self, fn, args) -> None:
        self._job = (fn, args)
        self._event.set()

    def _loop(self) -> None:
        while True:
            if not self._event.wait(self._pool.idle_s):
                # Idle expiry — but a submitter may have popped us off
                # the free list concurrently; in that race the job is
                # imminent and we must honor it.
                with self._pool._lock:
                    try:
                        self._pool._idle.remove(self)
                        claimed = False
                    except ValueError:
                        claimed = True
                if not claimed:
                    return
                self._event.wait()
            self._event.clear()
            fn, args = self._job
            self._job = None
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001 — match daemon threads
                traceback.print_exc()
            with self._pool._lock:
                self._pool._idle.append(self)


class _ThreadRecycler:
    """Unbounded thread pool with LIFO reuse and idle expiry.

    Same concurrency shape as thread-per-request — growth is unbounded,
    so queueing can never head-of-line-deadlock a nested call the way a
    capped executor would — but steady-state request dispatch reuses a
    parked thread instead of paying a thread spawn per call (reference:
    gRPC's completion-queue poller threads are long-lived, not
    per-request)."""

    def __init__(self, name: str, idle_s: float = 10.0):
        self.name = name
        self.idle_s = idle_s
        self._lock = lock_witness.Lock("rpc._ThreadRecycler")
        self._idle: list[_Recycled] = []
        # Reuse accounting: steady-state submitters should ride parked
        # threads (reuses), not pay spawns — the persistent-runner
        # stats (executor_stats()["pipeline"]) assert exactly that.
        self.spawns = 0
        self.reuses = 0

    def submit(self, fn, *args) -> None:
        with self._lock:
            worker = self._idle.pop() if self._idle else None
            if worker is None:
                self.spawns += 1
            else:
                self.reuses += 1
        if worker is None:
            worker = _Recycled(self)
        worker.run(fn, args)


# Shared by RPC servers (concurrent method dispatch) and the driver's
# remote-task launch path: at thousands of short calls per second the
# per-call thread spawn is a measurable fraction of the work.
DISPATCH_POOL = _ThreadRecycler("rpc-dispatch")


class RpcServer:
    """Serves registered callables; ``register_object`` exposes every
    public method of an object (the gRPC service pattern)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        self._methods: dict[str, Callable] = {}
        # Methods that may run long (task execution): dispatched off the
        # connection loop with out-of-order replies, so one connection
        # can carry many interleaved in-flight calls (the gRPC async
        # completion-queue shape — reference: src/ray/rpc/client_call.h).
        # "thread" = a thread per request (long blocking calls; bounded
        # upstream by admission); "pooled" = a small shared executor
        # (short calls like chunk fetches — no thread churn per chunk).
        self._concurrent: dict[str, str] = {}
        self._streaming: set[str] = set()
        self._io_pool = None
        self._io_pool_lock = lock_witness.Lock("rpc.RpcServer.io_pool")
        self._shutdown = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._conns: list[socket.socket] = []
        self._conns_lock = lock_witness.Lock("rpc.RpcServer.conns")
        # Optional reply metadata: when set (() -> dict), every plain
        # "ok" reply is tagged "okm" and carries (meta, result) — the
        # GCS server rides this to stamp its incarnation epoch on
        # every reply so clients detect a head restart on ANY call.
        # None (every other server) keeps replies byte-identical.
        self.reply_meta_fn: Callable[[], dict] | None = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, name: str, fn: Callable,
                 concurrent: "bool | str" = False,
                 streaming: bool = False) -> None:
        """``streaming=True``: the method is invoked with an extra
        ``_emit_part(payload)`` keyword that sends an intermediate
        ``part`` frame tagged with the call's seq — grouped progress
        replies flow while the call is still executing (the final
        return value resolves the call as usual). Callers must use
        MuxRpcClient.call_streaming to consume the parts."""
        self._methods[name] = fn
        if streaming:
            self._streaming.add(name)
        if concurrent:
            self._concurrent[name] = (
                concurrent if isinstance(concurrent, str) else "thread")

    def _get_io_pool(self):
        from concurrent.futures import ThreadPoolExecutor

        with self._io_pool_lock:
            if self._io_pool is None:
                from ray_tpu._private.config import GLOBAL_CONFIG

                self._io_pool = ThreadPoolExecutor(
                    max_workers=int(GLOBAL_CONFIG.rpc_io_pool_workers),
                    thread_name_prefix="rpc-io")
            return self._io_pool

    def register_object(self, obj: Any, prefix: str = "") -> None:
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self._methods[prefix + name] = fn

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rpc-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.5)
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rpc-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        send_lock = lock_witness.Lock(
            "rpc.RpcServer.conn_send")  # interleaved replies share the pipe
        try:
            while not self._shutdown.is_set():
                try:
                    frame = _recv_frame(conn)
                # OSError: the socket was closed under the loop
                # (stop(), a dispatch thread failing the conn) — same
                # terminal state as a peer-closed RpcError.
                except (RpcError, OSError):
                    return
                seq, method, args, kwargs = pickle.loads(frame)
                if method == "__batch__":
                    # Coalesced frame: many independently seq-tagged
                    # calls in one frame; each entry dispatches per its
                    # own method's concurrency mode and replies with its
                    # own seq — no batch-level reply exists.
                    for bseq, blob in args[0]:
                        try:
                            bmethod, bargs, bkwargs = pickle.loads(blob)
                        except Exception as exc:  # noqa: BLE001
                            if not self._reply(conn, send_lock, (
                                    bseq, "err",
                                    (RuntimeError(
                                        f"bad batch entry: {exc!r}"),
                                     ""))):
                                return
                            continue
                        if not self._dispatch(conn, send_lock, bseq,
                                              bmethod, bargs, bkwargs):
                            return
                    continue
                if not self._dispatch(conn, send_lock, seq, method,
                                      args, kwargs):
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass  # conn already torn down by the peer
            with self._conns_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass

    def _dispatch(self, conn, send_lock, seq, method, args,
                  kwargs) -> bool:
        """Route one decoded call per its method's concurrency mode.
        Returns False when the connection must be torn down."""
        mode = self._concurrent.get(method)
        if mode == "pooled":
            self._get_io_pool().submit(
                self._handle_one, conn, send_lock, seq, method,
                args, kwargs)
            return True
        if mode is not None:
            # Recycled threads: same unbounded thread-per-request shape
            # (no queueing deadlocks for nested calls), without a thread
            # spawn per call.
            DISPATCH_POOL.submit(
                self._handle_one, conn, send_lock, seq, method, args,
                kwargs)
            return True
        return self._handle_one(conn, send_lock, seq, method, args,
                                kwargs)

    def _send_tail(self, conn, send_lock, seq,
                   result: TailPayload) -> bool:
        """Emit a tail frame: [len][pickled (seq,'tail',(head,n))][raw
        tail bytes] — the payload crosses the socket straight from the
        server's buffer, no pickle memcpy on either side."""
        tail = result.tail if isinstance(result.tail, memoryview) \
            else memoryview(result.tail)
        head_blob = pickle.dumps((seq, "tail", (result.head,
                                                tail.nbytes)))
        try:
            with send_lock:
                conn.sendall(_LEN.pack(len(head_blob) + tail.nbytes))
                conn.sendall(head_blob)
                conn.sendall(tail)
            return True
        except OSError:
            try:
                conn.close()
            except OSError:
                pass  # close after send failure: already dead
            return False

    def _reply(self, conn, send_lock, reply) -> bool:
        try:
            blob = pickle.dumps(reply)
        except BaseException:  # noqa: BLE001
            return False
        try:
            with send_lock:
                _send_frame(conn, blob)
            return True
        except OSError:
            try:
                conn.close()
            except OSError:
                pass  # close after send failure: already dead
            return False

    def _handle_one(self, conn, send_lock, seq, method, args,
                    kwargs) -> bool:
        try:
            fn = self._methods[method]
        except KeyError:
            reply = (seq, "err", (KeyError(f"no method {method}"), ""))
        else:
            try:
                if method in self._streaming:
                    def _emit_part(payload) -> None:
                        # Chaos: kill the stream mid-parts — the
                        # producer aborts and the client sees the
                        # connection drop with parts outstanding (the
                        # TailPayload-death shape node death produces).
                        if chaos.ACTIVE is not None and \
                                chaos.ACTIVE.should("rpc.kill_stream"):
                            # shutdown before close: the conn-handler
                            # thread blocked in recv holds the socket
                            # open, so close() alone would never send
                            # the FIN the peer must observe.
                            try:
                                conn.shutdown(socket.SHUT_RDWR)
                            except OSError:
                                pass  # chaos kill: socket may already be dead
                            try:
                                conn.close()
                            except OSError:
                                pass  # chaos kill: socket may already be dead
                            raise RpcError("chaos: stream killed "
                                           "mid-parts")
                        # A dead connection must abort the producer, not
                        # let it stream into the void until completion.
                        if not self._reply(conn, send_lock,
                                           (seq, "part", payload)):
                            raise RpcError("connection lost mid-stream")

                    kwargs = dict(kwargs)
                    kwargs["_emit_part"] = _emit_part
                result = fn(*args, **kwargs)
                if isinstance(result, TailPayload):
                    return self._send_tail(conn, send_lock, seq, result)
                if self.reply_meta_fn is not None:
                    reply = (seq, "okm", (self.reply_meta_fn(), result))
                else:
                    reply = (seq, "ok", result)
            except BaseException as exc:  # noqa: BLE001
                tb = traceback.format_exc()
                try:
                    pickle.dumps(exc)
                except Exception:
                    exc = RuntimeError(f"{type(exc).__name__}: {exc}")
                reply = (seq, "err", (exc, tb))
        try:
            blob = pickle.dumps(reply)
        except BaseException as exc:  # noqa: BLE001 — reply unpicklable
            # The client MUST get a reply or its mux slot hangs for the
            # full call timeout; degrade to an error reply.
            reply = (seq, "err", (RuntimeError(
                f"reply serialization failed: {exc!r}"), ""))
            try:
                blob = pickle.dumps(reply)
            except BaseException:  # noqa: BLE001 — give up: kill the conn
                try:
                    conn.close()  # wakes every mux slot with RpcError
                except OSError:
                    pass  # conn already dead: slots fail either way
                return False
        try:
            with send_lock:
                _send_frame(conn, blob)
            return True
        except OSError:
            # A concurrent dispatch thread cannot signal the serve loop;
            # closing the socket fails the connection for everyone fast.
            try:
                conn.close()
            except OSError:
                pass  # conn already dead: that IS the signal
            return False

    def stop(self) -> None:
        self._shutdown.set()
        with self._io_pool_lock:
            if self._io_pool is not None:
                self._io_pool.shutdown(wait=False)
                self._io_pool = None
        try:
            self._sock.close()
        except OSError:
            pass  # listener already closed
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # peer already FINed the conn
            try:
                conn.close()
            except OSError:
                pass  # conn already closed


class _MuxSlot:
    """One in-flight pipelined call: a future the reader thread (or a
    connection failure) resolves. ``conn`` is None while the call sits
    in the coalescing queue, set once it is bound to a live socket."""

    __slots__ = ("event", "reply", "error", "client", "conn", "seq",
                 "method", "parts")

    def __init__(self, client: "MuxRpcClient", method: str):
        self.event = threading.Event()
        self.reply = None
        self.error: BaseException | None = None
        self.client = client
        self.conn: "_MuxConn | None" = None
        self.seq = 0
        self.method = method
        # Streaming calls only: a queue of intermediate "part" payloads
        # closed by _STREAM_END when the call resolves (either way).
        self.parts = None

    def done(self) -> bool:
        return self.event.is_set()

    def next_part(self, timeout_s: float | None = None):
        """Next intermediate payload of a streaming call, or None once
        the stream ended (then ``result()`` holds the final reply /
        raises the failure). Raises RpcError on timeout."""
        import queue as queue_mod

        try:
            item = self.parts.get(
                timeout=timeout_s if timeout_s is not None
                else self.client._timeout)
        except queue_mod.Empty:
            self.client._abandon(self)
            raise RpcError(
                f"rpc {self.method} to {self.client.address} stream "
                f"timed out") from None
        if item is _STREAM_END:
            return None
        return item

    def result(self, timeout_s: float | None = None) -> Any:
        client = self.client
        if not self.event.wait(timeout_s if timeout_s is not None
                               else client._timeout):
            client._abandon(self)
            raise RpcError(
                f"rpc {self.method} to {client.address} timed out",
                maybe_executed=True)
        if self.error is not None:
            # The request frame was written before the connection died
            # (pre-send failures raise synchronously in call_async), so
            # the method may have executed server-side.
            maybe = not isinstance(self.error, RpcError) \
                or self.error.maybe_executed
            raise RpcError(
                f"rpc {self.method} to {client.address} failed "
                f"(may have executed): {self.error}",
                maybe_executed=maybe) from self.error
        status, payload = self.reply
        if status == "err":
            exc, tb = payload
            raise RpcMethodError(exc, tb)
        return payload


class _MuxConn:
    """One live connection + the in-flight slots bound to IT. Slots are
    per-connection so a stale socket's failure can never wipe calls
    already riding a fresh reconnect."""

    __slots__ = ("sock", "pending")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.pending: dict[int, _MuxSlot] = {}


class MuxRpcClient:
    """One connection, MANY concurrent in-flight calls: requests are
    seq-tagged, a reader thread matches interleaved replies. This is the
    client half of the async completion-queue model (reference:
    src/ray/rpc/client_call.h) — N in-flight tasks to a node cost one
    socket, not N.

    The server must dispatch the called methods concurrently
    (RpcServer.register(..., concurrent=True)), or a long call would
    head-of-line block every other call on the connection.

    No transparent retry: once a request is written, a lost connection
    fails ALL in-flight calls with RpcError (the method may have
    executed — the caller owns the retry policy, as with RpcClient's
    after-send failures)."""

    def __init__(self, address: str, timeout_s: float = 24 * 3600.0,
                 connect_timeout_s: float = 10.0):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.address = f"{self._addr[0]}:{self._addr[1]}"
        self._timeout = timeout_s
        self._connect_timeout = connect_timeout_s
        self._lock = lock_witness.Lock(
            "rpc.MuxRpcClient.state")       # conn state + seq
        self._send_lock = lock_witness.Lock(
            "rpc.MuxRpcClient.send")  # frame writes
        self._conn: _MuxConn | None = None
        self._seq = 0
        self._closed = False
        # Coalescing queue: (slot, pre-pickled entry) pairs a flusher
        # thread packs into __batch__ frames — many control calls per
        # frame/syscall under bursts, zero added latency when idle
        # (natural batching: entries accumulate only while a previous
        # flush is in progress, plus the optional configured linger).
        self._batch_pending: list = []
        self._batch_event = threading.Event()
        self._batch_thread: threading.Thread | None = None
        # Reply-metadata listener: invoked (reader thread, must be
        # cheap and non-raising) with the meta dict of every "okm"
        # reply BEFORE the call's future resolves — epoch observers
        # see the bump no later than the call result.
        self.on_reply_meta: Callable[[dict], None] | None = None

    def _ensure_conn_locked(self) -> _MuxConn:
        # Caller holds self._lock.
        if self._conn is None:
            sock = socket.create_connection(
                self._addr, timeout=self._connect_timeout)
            sock.settimeout(None)  # reader blocks; call timeouts are
            sock.setsockopt(socket.IPPROTO_TCP,  # enforced on the slots
                            socket.TCP_NODELAY, 1)
            self._conn = _MuxConn(sock)
            threading.Thread(target=self._reader_loop,
                             args=(self._conn,), daemon=True,
                             name="mux-rpc-reader").start()
        return self._conn

    def call(self, method: str, *args, timeout_s: float | None = None,
             coalesce: bool = False, **kwargs) -> Any:
        return self.call_async(
            method, *args, coalesce=coalesce, **kwargs).result(timeout_s)

    def call_async(self, method: str, *args, coalesce: bool = False,
                   **kwargs) -> _MuxSlot:
        """Issue a pipelined call and return its future immediately.

        ``coalesce=True`` routes the call through the per-destination
        batching queue: it rides a shared __batch__ frame with whatever
        else is pending to this address (the right choice for chatty
        control messages — task submission, actor registration/calls);
        replies stay per-call. Latency-sensitive chunk fetches should
        keep the direct path."""
        if coalesce:
            return self._submit_coalesced(method, args, kwargs)
        return self._call_direct(method, args, kwargs)

    def call_streaming(self, method: str, *args, **kwargs) -> _MuxSlot:
        """Issue a call whose server method streams intermediate
        ``part`` frames (RpcServer.register(..., streaming=True)).
        Consume them with ``slot.next_part()`` until it returns None,
        then ``slot.result()`` for the final reply."""
        import queue as queue_mod

        slot = self._call_direct(method, args, kwargs,
                                 parts=queue_mod.SimpleQueue())
        return slot

    def _call_direct(self, method: str, args, kwargs,
                     parts=None) -> _MuxSlot:
        slot = _MuxSlot(self, method)
        slot.parts = parts
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.address} is closed")
            try:
                conn = self._ensure_conn_locked()
            except OSError as exc:
                raise RpcError(
                    f"cannot connect to {self.address}: {exc}") from exc
            self._seq += 1
            slot.seq = self._seq
        # Pickle BEFORE registering the slot: an unpicklable argument
        # must raise cleanly, not leak a pending entry per attempt.
        request = pickle.dumps((slot.seq, method, args, kwargs))
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.address} is closed")
            slot.conn = conn
            conn.pending[slot.seq] = slot
        if chaos.ACTIVE is not None:
            # Chaos sites on the request path: sever the connection
            # (every in-flight call fails like a node death), drop just
            # this frame (the call times out — a lost packet the
            # transport never detects), or delay the send.
            # net.partition: a SUSTAINED window — while it is open,
            # every send to this destination dies like a cut link
            # (in-flight calls fail with it), and the link heals in
            # place when the seeded window expires.
            if chaos.ACTIVE.partitioned(self.address) \
                    or chaos.ACTIVE.maybe_partition(self.address):
                self._fail_conn(conn, RpcError("chaos: net.partition"))
                raise RpcError(
                    f"rpc {method} to {self.address} failed: chaos "
                    f"net.partition window open")
            if chaos.ACTIVE.should("rpc.sever"):
                self._fail_conn(conn, RpcError("chaos: severed"))
                raise RpcError(
                    f"rpc {method} to {self.address} failed: "
                    f"chaos severed the connection")
            if chaos.ACTIVE.should("rpc.drop_frame"):
                return slot  # never sent; resolves by timeout/sever
            if chaos.ACTIVE.should("rpc.delay"):
                time.sleep(0.005 + 0.045 * chaos.ACTIVE.uniform())
        try:
            with self._send_lock:
                _send_frame(conn.sock, request)
        except OSError as exc:
            self._fail_conn(conn, exc)
            raise RpcError(
                f"rpc {method} to {self.address} failed: {exc}",
                maybe_executed=True) from exc
        return slot

    def _abandon(self, slot: _MuxSlot) -> None:
        """A caller gave up on the slot (timeout): unregister it so the
        pending table (or coalescing queue) doesn't leak the entry."""
        with self._lock:
            if slot.conn is not None:
                slot.conn.pending.pop(slot.seq, None)
            else:
                self._batch_pending = [
                    (s, b) for s, b in self._batch_pending if s is not slot]

    # -- coalescing -------------------------------------------------------

    def _submit_coalesced(self, method: str, args, kwargs) -> _MuxSlot:
        # Per-entry pickling happens on the caller's thread: a bad
        # argument fails ITS caller, never poisons batch-mates.
        blob = pickle.dumps((method, args, kwargs))
        slot = _MuxSlot(self, method)
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.address} is closed")
            # Adaptive: an UNCONTENDED socket with nothing queued sends
            # immediately (a steady trickle pays zero batching tax);
            # under contention — a writer mid-frame, i.e. a burst —
            # entries queue and ride shared frames. Queue-empty is
            # required for the direct path so per-destination enqueue
            # order is never reordered around queued entries.
            direct = (not self._batch_pending
                      and self._send_lock.acquire(blocking=False))
            if direct:
                try:
                    conn = self._ensure_conn_locked()
                    self._seq += 1
                    slot.seq = self._seq
                    slot.conn = conn
                    conn.pending[slot.seq] = slot
                except OSError as exc:
                    self._send_lock.release()
                    raise RpcError(
                        f"cannot connect to {self.address}: "
                        f"{exc}") from exc
            else:
                self._batch_pending.append((slot, blob))
                if self._batch_thread is None:
                    self._batch_thread = threading.Thread(
                        target=self._flush_loop, daemon=True,
                        name="mux-rpc-flusher")
                    self._batch_thread.start()
        if not direct:
            self._batch_event.set()
            return slot
        frame = pickle.dumps((0, "__batch__", (((slot.seq, blob),),),
                              {}))
        try:
            _send_frame(conn.sock, frame)
        except OSError as exc:
            self._send_lock.release()
            self._fail_conn(conn, exc)
            return slot
        self._send_lock.release()
        return slot

    @staticmethod
    def _batch_limits() -> tuple[float, int]:
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG

            return (float(GLOBAL_CONFIG.rpc_batch_flush_ms) / 1000.0,
                    int(GLOBAL_CONFIG.rpc_batch_max_entries))
        except Exception:  # noqa: BLE001 — config unavailable mid-teardown
            return 0.0, 128

    def _flush_loop(self) -> None:
        import time as time_mod

        while True:
            self._batch_event.wait()
            linger, max_entries = self._batch_limits()
            if linger > 0:
                time_mod.sleep(linger)
            with self._lock:
                self._batch_event.clear()
                pending, self._batch_pending = self._batch_pending, []
                closed = self._closed
            if closed:
                for slot, _ in pending:
                    slot.error = RpcError("client closed")
                    slot.event.set()
                return
            while pending:
                self._flush_batch(pending[:max_entries])
                pending = pending[max_entries:]

    def _flush_batch(self, pending: list) -> None:
        with self._lock:
            if self._closed:
                conn = None
            else:
                try:
                    conn = self._ensure_conn_locked()
                except OSError as exc:
                    conn = None
                    # Never sent: provably retryable.
                    error: BaseException = RpcError(
                        f"cannot connect to {self.address}: {exc}")
            if conn is None:
                if self._closed:
                    error = RpcError("client closed")
                for slot, _ in pending:
                    slot.error = error
                    slot.event.set()
                return
            entries = []
            for slot, blob in pending:
                self._seq += 1
                slot.seq = self._seq
                slot.conn = conn
                conn.pending[slot.seq] = slot
                entries.append((slot.seq, blob))
        frame = pickle.dumps((0, "__batch__", (entries,), {}))
        try:
            with self._send_lock:
                _send_frame(conn.sock, frame)
        except OSError as exc:
            self._fail_conn(conn, exc)

    def _reader_loop(self, conn: _MuxConn) -> None:
        while True:
            try:
                frame = _recv_frame(conn.sock)
            except (RpcError, OSError) as exc:
                self._fail_conn(conn, exc)
                return
            try:
                # Tail frames carry raw payload bytes after the pickle;
                # loads ignores the trailing data.
                seq, status, payload = pickle.loads(frame)
                if status == "tail":
                    head, tail_len = payload
                    status = "ok"
                    payload = (head, memoryview(frame)[-tail_len:]
                               if tail_len else b"")
                elif status == "okm":
                    meta, payload = payload
                    status = "ok"
                    cb = self.on_reply_meta
                    if cb is not None:
                        try:
                            cb(meta)
                        except Exception:  # noqa: BLE001 — observer only
                            pass
            except Exception as exc:  # noqa: BLE001 — corrupt stream
                self._fail_conn(conn, exc)
                return
            if status == "part":
                # Intermediate streaming payload: the call stays
                # pending; deliver to its parts queue.
                with self._lock:
                    slot = conn.pending.get(seq)
                if slot is not None and slot.parts is not None:
                    slot.parts.put(payload)
                continue
            with self._lock:
                slot = conn.pending.pop(seq, None)
            if slot is not None:
                slot.reply = (status, payload)
                slot.event.set()
                if slot.parts is not None:
                    slot.parts.put(_STREAM_END)

    def _fail_conn(self, conn: _MuxConn, exc: BaseException) -> None:
        """Fail exactly the calls riding THIS connection; calls on a
        reconnected successor are untouched. Slots bound to a live
        connection had their request frames written, so their failure
        is classified maybe-executed (only idempotent callers retry)."""
        with self._lock:
            if self._conn is conn:
                self._conn = None  # next call reconnects fresh
            pending = list(conn.pending.values())
            conn.pending.clear()
        try:
            conn.sock.close()
        except OSError:
            pass  # socket already dead: callers get RpcError
        if not (isinstance(exc, RpcError) and exc.maybe_executed):
            exc = RpcError(f"connection lost with the call in flight: "
                           f"{exc}", maybe_executed=True)
        for slot in pending:
            slot.error = exc
            slot.event.set()
            if slot.parts is not None:
                slot.parts.put(_STREAM_END)

    def ping(self) -> bool:
        try:
            return self.call("ping", timeout_s=5.0) == "pong"
        except (RpcError, RpcMethodError):
            return False

    def num_connections(self) -> int:
        with self._lock:
            return 1 if self._conn is not None else 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conn, self._conn = self._conn, None
            pending = list(conn.pending.values()) if conn else []
            if conn:
                conn.pending.clear()
            queued, self._batch_pending = self._batch_pending, []
        self._batch_event.set()  # flusher observes _closed and exits
        if conn is not None:
            try:
                conn.sock.close()
            except OSError:
                pass  # close on shutdown: already dead is fine
        for slot in pending + [s for s, _ in queued]:
            slot.error = RpcError("client closed")
            slot.event.set()
            if slot.parts is not None:
                slot.parts.put(_STREAM_END)


class RpcClient:
    """One connection; calls are serialized (seq-matched replies)."""

    def __init__(self, address: str, timeout_s: float = 30.0,
                 connect_timeout_s: float | None = None):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.address = f"{self._addr[0]}:{self._addr[1]}"
        self._timeout = timeout_s
        # Long read timeouts (blocking task executions) must not make
        # CONNECTING to a dead host block equally long.
        self._connect_timeout = (connect_timeout_s
                                 if connect_timeout_s is not None
                                 else min(timeout_s, 10.0))
        self._lock = lock_witness.Lock("rpc.RpcClient")
        self._sock: socket.socket | None = None
        self._seq = 0
        # Same reply-metadata hook as MuxRpcClient (invoked on the
        # calling thread, before the result returns).
        self.on_reply_meta: Callable[[dict], None] | None = None

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            self._addr, timeout=self._connect_timeout)
        sock.settimeout(self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    @staticmethod
    def _stale(sock: socket.socket) -> bool:
        """A pooled idle socket with a pending EOF/RST shows readable
        (no reply is outstanding, so ANY readability means the peer
        closed). Detecting this before send keeps the common
        server-restart case retriable without double-execution risk."""
        try:
            readable, _, _ = select.select([sock], [], [], 0)
            return bool(readable)
        except (OSError, ValueError):
            return True

    def call(self, method: str, *args, **kwargs) -> Any:
        with self._lock:
            self._seq += 1
            seq = self._seq
            request = pickle.dumps((seq, method, args, kwargs))
            last_exc: Exception | None = None
            for attempt in range(2):  # one transparent reconnect
                # Retry is safe ONLY while the server cannot have executed
                # the request: before the full frame was handed to the
                # kernel. Once sendall returns, a lost reply may mean the
                # method ran — surface RpcError instead of re-sending
                # (non-idempotent methods would double-execute).
                sent = False
                try:
                    if self._sock is not None and self._stale(self._sock):
                        try:
                            self._sock.close()
                        except OSError:
                            pass  # stale socket: replaced below either way
                        self._sock = None
                    if self._sock is None:
                        self._sock = self._connect()
                    _send_frame(self._sock, request)
                    sent = True
                    frame = _recv_frame(self._sock)
                    rseq, status, payload = pickle.loads(frame)
                    if status == "tail":
                        head, tail_len = payload
                        status = "ok"
                        payload = (head, memoryview(frame)[-tail_len:]
                                   if tail_len else b"")
                    elif status == "okm":
                        meta, payload = payload
                        status = "ok"
                        if self.on_reply_meta is not None:
                            try:
                                self.on_reply_meta(meta)
                            except Exception:  # noqa: BLE001
                                pass
                    if rseq != seq:
                        raise RpcError(
                            f"out-of-order reply: {rseq} != {seq}")
                    break
                except (OSError, RpcError, EOFError) as exc:
                    last_exc = exc
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass  # failed socket: retry mints a new one
                        self._sock = None
                    if sent:
                        raise RpcError(
                            f"rpc {method} to {self.address} failed after "
                            f"send (may have executed): {exc}",
                            maybe_executed=True) from exc
            else:
                raise RpcError(
                    f"rpc to {self.address} failed: {last_exc}") \
                    from last_exc
        if status == "err":
            exc, tb = payload
            raise RpcMethodError(exc, tb)
        return payload

    def ping(self) -> bool:
        try:
            return self.call("ping") == "pong"
        except (RpcError, RpcMethodError):
            return False

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass  # close(): already-closed is the goal state
                self._sock = None
