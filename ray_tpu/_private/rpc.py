"""RPC layer — length-prefixed pickle messages over TCP.

TPU-native analogue of the reference's gRPC plumbing
(src/ray/rpc/grpc_server.h, grpc_client.h): every cross-process control
message in the reference is protobuf-over-gRPC; here it is
pickle-over-TCP with an 8-byte length prefix. Pickle is acceptable for
the same reason the reference ships cloudpickle blobs inside its
protobufs: cluster links are trusted (same security model).

Server: thread-per-connection, sequential dispatch per connection (the
reference's gRPC servers are also ordered per stream). Client: one
socket, calls serialized under a lock, transparent reconnect on a dead
socket (e.g. head restarted).
"""

from __future__ import annotations

import pickle
import select
import socket
import struct
import threading
import traceback
from typing import Any, Callable

_LEN = struct.Struct(">Q")
MAX_FRAME = 1 << 31  # 2GB sanity bound


class RpcError(ConnectionError):
    """Transport-level failure (peer unreachable / connection lost)."""


class RpcMethodError(Exception):
    """The remote method raised; carries the remote traceback."""

    def __init__(self, cause: BaseException, remote_tb: str):
        super().__init__(f"{type(cause).__name__}: {cause}")
        self.cause = cause
        self.remote_tb = remote_tb

    def __reduce__(self):
        # Exception's default reduce re-calls __init__ with args=(the
        # formatted message,) — one argument short; an RpcMethodError
        # crossing ANOTHER pickle boundary (e.g. stored as a task error
        # and shipped to a different process) must round-trip.
        return (RpcMethodError, (self.cause, self.remote_tb))


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise RpcError("connection closed by peer")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return _recv_exact(sock, length)


class RpcServer:
    """Serves registered callables; ``register_object`` exposes every
    public method of an object (the gRPC service pattern)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        self._methods: dict[str, Callable] = {}
        # Methods that may run long (task execution): dispatched off the
        # connection loop with out-of-order replies, so one connection
        # can carry many interleaved in-flight calls (the gRPC async
        # completion-queue shape — reference: src/ray/rpc/client_call.h).
        # "thread" = a thread per request (long blocking calls; bounded
        # upstream by admission); "pooled" = a small shared executor
        # (short calls like chunk fetches — no thread churn per chunk).
        self._concurrent: dict[str, str] = {}
        self._io_pool = None
        self._io_pool_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, name: str, fn: Callable,
                 concurrent: "bool | str" = False) -> None:
        self._methods[name] = fn
        if concurrent:
            self._concurrent[name] = (
                concurrent if isinstance(concurrent, str) else "thread")

    def _get_io_pool(self):
        from concurrent.futures import ThreadPoolExecutor

        with self._io_pool_lock:
            if self._io_pool is None:
                from ray_tpu._private.config import GLOBAL_CONFIG

                self._io_pool = ThreadPoolExecutor(
                    max_workers=int(GLOBAL_CONFIG.rpc_io_pool_workers),
                    thread_name_prefix="rpc-io")
            return self._io_pool

    def register_object(self, obj: Any, prefix: str = "") -> None:
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self._methods[prefix + name] = fn

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rpc-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.5)
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rpc-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()  # interleaved replies share the pipe
        try:
            while not self._shutdown.is_set():
                try:
                    frame = _recv_frame(conn)
                except RpcError:
                    return
                seq, method, args, kwargs = pickle.loads(frame)
                mode = self._concurrent.get(method)
                if mode == "pooled":
                    self._get_io_pool().submit(
                        self._handle_one, conn, send_lock, seq, method,
                        args, kwargs)
                    continue
                if mode is not None:
                    threading.Thread(
                        target=self._handle_one,
                        args=(conn, send_lock, seq, method, args, kwargs),
                        daemon=True, name=f"rpc-{method}").start()
                    continue
                if not self._handle_one(conn, send_lock, seq, method,
                                        args, kwargs):
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass

    def _handle_one(self, conn, send_lock, seq, method, args,
                    kwargs) -> bool:
        try:
            fn = self._methods[method]
        except KeyError:
            reply = (seq, "err", (KeyError(f"no method {method}"), ""))
        else:
            try:
                reply = (seq, "ok", fn(*args, **kwargs))
            except BaseException as exc:  # noqa: BLE001
                tb = traceback.format_exc()
                try:
                    pickle.dumps(exc)
                except Exception:
                    exc = RuntimeError(f"{type(exc).__name__}: {exc}")
                reply = (seq, "err", (exc, tb))
        try:
            blob = pickle.dumps(reply)
        except BaseException as exc:  # noqa: BLE001 — reply unpicklable
            # The client MUST get a reply or its mux slot hangs for the
            # full call timeout; degrade to an error reply.
            reply = (seq, "err", (RuntimeError(
                f"reply serialization failed: {exc!r}"), ""))
            try:
                blob = pickle.dumps(reply)
            except BaseException:  # noqa: BLE001 — give up: kill the conn
                try:
                    conn.close()  # wakes every mux slot with RpcError
                except OSError:
                    pass
                return False
        try:
            with send_lock:
                _send_frame(conn, blob)
            return True
        except OSError:
            # A concurrent dispatch thread cannot signal the serve loop;
            # closing the socket fails the connection for everyone fast.
            try:
                conn.close()
            except OSError:
                pass
            return False

    def stop(self) -> None:
        self._shutdown.set()
        with self._io_pool_lock:
            if self._io_pool is not None:
                self._io_pool.shutdown(wait=False)
                self._io_pool = None
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


class _MuxSlot:
    __slots__ = ("event", "reply", "error")

    def __init__(self):
        self.event = threading.Event()
        self.reply = None
        self.error: BaseException | None = None


class _MuxConn:
    """One live connection + the in-flight slots bound to IT. Slots are
    per-connection so a stale socket's failure can never wipe calls
    already riding a fresh reconnect."""

    __slots__ = ("sock", "pending")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.pending: dict[int, _MuxSlot] = {}


class MuxRpcClient:
    """One connection, MANY concurrent in-flight calls: requests are
    seq-tagged, a reader thread matches interleaved replies. This is the
    client half of the async completion-queue model (reference:
    src/ray/rpc/client_call.h) — N in-flight tasks to a node cost one
    socket, not N.

    The server must dispatch the called methods concurrently
    (RpcServer.register(..., concurrent=True)), or a long call would
    head-of-line block every other call on the connection.

    No transparent retry: once a request is written, a lost connection
    fails ALL in-flight calls with RpcError (the method may have
    executed — the caller owns the retry policy, as with RpcClient's
    after-send failures)."""

    def __init__(self, address: str, timeout_s: float = 24 * 3600.0,
                 connect_timeout_s: float = 10.0):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.address = f"{self._addr[0]}:{self._addr[1]}"
        self._timeout = timeout_s
        self._connect_timeout = connect_timeout_s
        self._lock = threading.Lock()       # conn state + seq
        self._send_lock = threading.Lock()  # frame writes
        self._conn: _MuxConn | None = None
        self._seq = 0
        self._closed = False

    def _ensure_conn(self) -> _MuxConn:
        # Caller holds self._lock.
        if self._conn is None:
            sock = socket.create_connection(
                self._addr, timeout=self._connect_timeout)
            sock.settimeout(None)  # reader blocks; call timeouts are
            sock.setsockopt(socket.IPPROTO_TCP,  # enforced on the slots
                            socket.TCP_NODELAY, 1)
            self._conn = _MuxConn(sock)
            threading.Thread(target=self._reader_loop,
                             args=(self._conn,), daemon=True,
                             name="mux-rpc-reader").start()
        return self._conn

    def call(self, method: str, *args, timeout_s: float | None = None,
             **kwargs) -> Any:
        slot = _MuxSlot()
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.address} is closed")
            try:
                conn = self._ensure_conn()
            except OSError as exc:
                raise RpcError(
                    f"cannot connect to {self.address}: {exc}") from exc
            self._seq += 1
            seq = self._seq
        # Pickle BEFORE registering the slot: an unpicklable argument
        # must raise cleanly, not leak a pending entry per attempt.
        request = pickle.dumps((seq, method, args, kwargs))
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.address} is closed")
            conn.pending[seq] = slot
        try:
            with self._send_lock:
                _send_frame(conn.sock, request)
        except OSError as exc:
            self._fail_conn(conn, exc)
            raise RpcError(
                f"rpc {method} to {self.address} failed: {exc}") from exc
        if not slot.event.wait(timeout_s if timeout_s is not None
                               else self._timeout):
            with self._lock:
                conn.pending.pop(seq, None)
            raise RpcError(
                f"rpc {method} to {self.address} timed out")
        if slot.error is not None:
            raise RpcError(
                f"rpc {method} to {self.address} failed "
                f"(may have executed): {slot.error}") from slot.error
        status, payload = slot.reply
        if status == "err":
            exc, tb = payload
            raise RpcMethodError(exc, tb)
        return payload

    def _reader_loop(self, conn: _MuxConn) -> None:
        while True:
            try:
                frame = _recv_frame(conn.sock)
            except (RpcError, OSError) as exc:
                self._fail_conn(conn, exc)
                return
            try:
                seq, status, payload = pickle.loads(frame)
            except Exception as exc:  # noqa: BLE001 — corrupt stream
                self._fail_conn(conn, exc)
                return
            with self._lock:
                slot = conn.pending.pop(seq, None)
            if slot is not None:
                slot.reply = (status, payload)
                slot.event.set()

    def _fail_conn(self, conn: _MuxConn, exc: BaseException) -> None:
        """Fail exactly the calls riding THIS connection; calls on a
        reconnected successor are untouched."""
        with self._lock:
            if self._conn is conn:
                self._conn = None  # next call reconnects fresh
            pending = list(conn.pending.values())
            conn.pending.clear()
        try:
            conn.sock.close()
        except OSError:
            pass
        for slot in pending:
            slot.error = exc
            slot.event.set()

    def ping(self) -> bool:
        try:
            return self.call("ping", timeout_s=5.0) == "pong"
        except (RpcError, RpcMethodError):
            return False

    def num_connections(self) -> int:
        with self._lock:
            return 1 if self._conn is not None else 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conn, self._conn = self._conn, None
            pending = list(conn.pending.values()) if conn else []
            if conn:
                conn.pending.clear()
        if conn is not None:
            try:
                conn.sock.close()
            except OSError:
                pass
        for slot in pending:
            slot.error = RpcError("client closed")
            slot.event.set()


class RpcClient:
    """One connection; calls are serialized (seq-matched replies)."""

    def __init__(self, address: str, timeout_s: float = 30.0,
                 connect_timeout_s: float | None = None):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.address = f"{self._addr[0]}:{self._addr[1]}"
        self._timeout = timeout_s
        # Long read timeouts (blocking task executions) must not make
        # CONNECTING to a dead host block equally long.
        self._connect_timeout = (connect_timeout_s
                                 if connect_timeout_s is not None
                                 else min(timeout_s, 10.0))
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._seq = 0

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            self._addr, timeout=self._connect_timeout)
        sock.settimeout(self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    @staticmethod
    def _stale(sock: socket.socket) -> bool:
        """A pooled idle socket with a pending EOF/RST shows readable
        (no reply is outstanding, so ANY readability means the peer
        closed). Detecting this before send keeps the common
        server-restart case retriable without double-execution risk."""
        try:
            readable, _, _ = select.select([sock], [], [], 0)
            return bool(readable)
        except (OSError, ValueError):
            return True

    def call(self, method: str, *args, **kwargs) -> Any:
        with self._lock:
            self._seq += 1
            seq = self._seq
            request = pickle.dumps((seq, method, args, kwargs))
            last_exc: Exception | None = None
            for attempt in range(2):  # one transparent reconnect
                # Retry is safe ONLY while the server cannot have executed
                # the request: before the full frame was handed to the
                # kernel. Once sendall returns, a lost reply may mean the
                # method ran — surface RpcError instead of re-sending
                # (non-idempotent methods would double-execute).
                sent = False
                try:
                    if self._sock is not None and self._stale(self._sock):
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    if self._sock is None:
                        self._sock = self._connect()
                    _send_frame(self._sock, request)
                    sent = True
                    rseq, status, payload = pickle.loads(
                        _recv_frame(self._sock))
                    if rseq != seq:
                        raise RpcError(
                            f"out-of-order reply: {rseq} != {seq}")
                    break
                except (OSError, RpcError, EOFError) as exc:
                    last_exc = exc
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    if sent:
                        raise RpcError(
                            f"rpc {method} to {self.address} failed after "
                            f"send (may have executed): {exc}") from exc
            else:
                raise RpcError(
                    f"rpc to {self.address} failed: {last_exc}") \
                    from last_exc
        if status == "err":
            exc, tb = payload
            raise RpcMethodError(exc, tb)
        return payload

    def ping(self) -> bool:
        try:
            return self.call("ping") == "pong"
        except (RpcError, RpcMethodError):
            return False

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
