"""Crash flight recorder: a bounded per-process ring of lifecycle /
fault / chaos events, persisted to the session dir so post-mortems
survive SIGKILL.

Reference intent: the reference's event/export surface (``ray_tpu
debug`` plays the role of `ray cluster-dump`): when a daemon dies —
gracefully, fatally, or by SIGKILL — the operator wants the last N
things that process saw WITHOUT having had debug logging armed.

Cost discipline:

- ``record(kind, *args)`` on the hot-ish paths appends a raw tuple
  ``(ts, kind, args)`` to a bounded ``deque`` — no formatting, no I/O,
  no lock (deque.append is atomic under the GIL). Formatting happens
  only at dump time.
- Daemons run a flusher thread (``flight_recorder_flush_s``) that
  rewrites this process's ring file when new events arrived, plus one
  dump at install — so a SIGKILLed daemon's ring is on disk within one
  flush period of its last event. Drivers and pool workers install
  WITHOUT a flusher (their rings are read live by ``ray_tpu debug`` /
  the ``flight_ring`` RPC, and dumped only on fatal errors) so a busy
  test box isn't littered with per-driver files.

Ring files live under ``$RAY_TPU_SESSION_DIR/flight/<role>-<pid>.json``
and carry the ring plus the process's fault counters, breaker state,
spill-tier counters and recent stage histograms; ``python -m ray_tpu
debug`` collects the files and every reachable process's LIVE ring
into one bundle.

Record sites: chaos firings, breaker opens (rpc.py), worker crashes,
node death, object loss, heartbeat re-registration, daemon stop, the
spill tier's lifecycle (``spill.spill`` / ``spill.restore`` /
``spill.evict`` / ``spill.torn`` / ``spill.disk_full`` /
``spill.orphan_sweep`` — spill_manager.py), and the durable control
plane (``gcs.restore`` / ``gcs.torn_snapshot`` / ``gcs.persist_error``
/ ``gcs.fenced_write`` head-side; ``gcs.shard_restore`` /
``gcs.shard_fenced_write`` / ``gcs.shard_backoff`` on a sharded
head's failover/degraded paths; ``epoch.bump`` /
``heartbeat.stale_epoch`` / ``gcs.stale_epoch`` / ``heartbeat.shed``
on daemons and drivers re-syncing across a head or shard restart), so
a post-mortem shows what the disk tier and the head's recovery were
doing when the process died. The head's health watchdog
(metrics_history.py) records ``health.<rule>`` — one event per typed
verdict ACTIVATION (rule, node, value), for each rule in
``HEALTH_RULES`` — so a post-mortem shows which SLO verdicts fired
and when, even if the head died before anyone ran ``doctor``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque


def _session_dir() -> str:
    return os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")


def flight_dir() -> str:
    return os.path.join(_session_dir(), "flight")


class FlightRecorder:
    def __init__(self, role: str, capacity: int = 512,
                 flush_period_s: float = 0.0,
                 extra_fn=None):
        self.role = role
        self.pid = os.getpid()
        self.started_at = time.time()
        self._ring: deque = deque(maxlen=max(8, int(capacity)))
        # Extra state included in dumps: () -> dict (fault counters,
        # breaker state, stage histograms — wired at the install site).
        self._extra_fn = extra_fn
        self._flushed_len = -1
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if flush_period_s and flush_period_s > 0:
            self.arm_flush(float(flush_period_s))

    def arm_flush(self, period_s: float) -> None:
        """Start (idempotently) the flusher thread — lets a process
        install the recorder EARLY (so boot-time events like the GCS
        restore land in the ring) and arm persistence once the rest of
        the daemon is up."""
        if self._thread is not None or period_s <= 0:
            return
        self._thread = threading.Thread(
            target=self._flush_loop, args=(float(period_s),),
            daemon=True, name="flight-recorder")
        self._thread.start()

    # ------------------------------------------------------------- hot path

    def record(self, kind: str, *args) -> None:
        self._ring.append((time.time(), kind, args))

    # ---------------------------------------------------------------- dumps

    def snapshot(self) -> dict:
        """The ring + process state as plain data (events formatted
        HERE, never on the record path)."""
        events = [{"ts": ts, "kind": kind,
                   "args": [str(a) for a in args]}
                  for ts, kind, args in list(self._ring)]
        extra = {}
        if self._extra_fn is not None:
            try:
                extra = self._extra_fn() or {}
            except Exception:  # noqa: BLE001 — dump must never raise
                extra = {}
        return {"role": self.role, "pid": self.pid,
                "started_at": self.started_at, "events": events,
                **extra}

    def path(self) -> str:
        return os.path.join(flight_dir(), f"{self.role}-{self.pid}.json")

    def dump(self, reason: str) -> str | None:
        """Write the ring file atomically (tmp+rename); returns the
        path, or None when the session dir is unwritable."""
        snap = self.snapshot()
        snap["reason"] = reason
        snap["dumped_at"] = time.time()
        path = self.path()
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, path)
        except OSError:
            return None
        self._flushed_len = len(self._ring)
        return path

    def _flush_loop(self, period_s: float) -> None:
        # Immediate first dump: a daemon SIGKILLed between install and
        # the first period must still leave its boot events on disk.
        self.dump("periodic")
        while not self._stop.wait(period_s):
            if len(self._ring) != self._flushed_len:
                self.dump("periodic")

    def stop(self) -> None:
        self._stop.set()


# --------------------------------------------------------------------------
# Process singleton
# --------------------------------------------------------------------------

_REC: FlightRecorder | None = None


def install(role: str, flush: bool = False, extra_fn=None
            ) -> FlightRecorder:
    """Install the process-wide recorder (idempotent per process —
    a re-init keeps the existing ring so events survive driver
    shutdown/init cycles within one process). A re-install UPGRADES in
    place: the head installs a bare ring before the GCS restore (so
    recovery events are captured) and the later daemon install arms
    flushing + dump enrichment without losing those events."""
    global _REC
    from ray_tpu._private.config import GLOBAL_CONFIG

    if _REC is not None:
        if extra_fn is not None and _REC._extra_fn is None:
            _REC._extra_fn = extra_fn
        if flush and _REC._thread is None:
            _prune_stale_dumps()
            _REC.arm_flush(float(
                GLOBAL_CONFIG.flight_recorder_flush_s or 0.0))
        return _REC
    capacity = int(GLOBAL_CONFIG.flight_recorder_events or 512)
    period = float(GLOBAL_CONFIG.flight_recorder_flush_s or 0.0) \
        if flush else 0.0
    if flush:
        _prune_stale_dumps()
    _REC = FlightRecorder(role, capacity=capacity,
                          flush_period_s=period, extra_fn=extra_fn)
    _REC.record("start", role)
    return _REC


def _prune_stale_dumps(max_age_s: float = 3 * 86400) -> None:
    """Best-effort sweep of days-old ring files: the session dir is
    shared across sessions, and a machine cycling many daemons must
    not accumulate dumps forever. Recent files stay — they are the
    post-mortems `ray_tpu debug` exists to collect."""
    try:
        names = os.listdir(flight_dir())
    except OSError:
        return
    cutoff = time.time() - max_age_s
    for name in names:
        path = os.path.join(flight_dir(), name)
        try:
            if os.path.getmtime(path) < cutoff:
                os.unlink(path)
        except OSError:
            continue  # raced with another pruner / RO fs


def get() -> FlightRecorder | None:
    return _REC


def record(kind: str, *args) -> None:
    """Module-level record: one attribute load + a deque append when a
    recorder is installed, one branch when not."""
    rec = _REC
    if rec is not None:
        rec._ring.append((time.time(), kind, args))


def dump(reason: str) -> str | None:
    rec = _REC
    return rec.dump(reason) if rec is not None else None


def collect_session_dumps() -> list[dict]:
    """Parse every ring file under the session dir (dead processes'
    flushed rings included). Malformed/partial files are skipped."""
    out: list[dict] = []
    try:
        names = sorted(os.listdir(flight_dir()))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(flight_dir(), name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # malformed or mid-write ring file: skip
        if isinstance(doc, dict):
            doc["file"] = name
            out.append(doc)
    return out
