"""Fork-server worker factory: millisecond worker/actor process spawn.

Reference: Ray keeps worker startup off the task critical path with
prestarted language workers (src/ray/raylet/worker_pool.h "Starts a
number of workers ahead of time"). This is the TPU-native single-box
analogue taken further: instead of paying a full interpreter boot +
framework import (~1-2s of CPU) per worker, each daemon runs ONE
pre-imported template process; every subsequent worker is an os.fork()
of it (~10ms, memory shared copy-on-write). On the 1-to-few-core hosts
that drive TPU slices this is the difference between actor creation at
~1/s and ~50/s.

Topology:
  daemon/driver process
    └─ factory (python -m ray_tpu._private.worker_factory <sock> <ppid>)
         ├─ forked worker 1  ── connects back to the pool's Listener
         ├─ forked worker 2     and runs worker_pool.worker_main, byte-
         └─ ...                 identical to a Popen'd worker from there

The factory is single-threaded (fork-safe by construction), reaps its
children, and exits when its parent dies (ppid watch). Spawn requests
ride one-shot connections on a 0700-dir unix socket. Workers needing a
TPU (allow_tpu=True) or a different interpreter never use the factory —
callers fall back to the subprocess path.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import sys
import time

_LEN = struct.Struct(">I")


def _send_msg(sock: socket.socket, obj) -> None:
    blob = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_msg(sock: socket.socket):
    buf = b""
    while len(buf) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(buf))
        if not chunk:
            raise EOFError("factory peer closed")
        buf += chunk
    (length,) = _LEN.unpack(buf)
    parts = []
    while length > 0:
        chunk = sock.recv(min(length, 1 << 20))
        if not chunk:
            raise EOFError("factory peer closed")
        parts.append(chunk)
        length -= len(chunk)
    return pickle.loads(b"".join(parts))


# --------------------------------------------------------------------------
# Factory process
# --------------------------------------------------------------------------


def _child_exec(req: dict, pipe_fd: int | None = None) -> None:
    """Post-fork setup then the normal worker serve loop. Never returns."""
    rc = 1
    try:
        import gc
        import signal

        # The template disabled gc around its freeze(); workers do real
        # work and must collect cycles again (frozen template objects
        # stay permanent — the child never pages them in via gc).
        gc.enable()
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        env = req.get("env") or {}
        # REPLACE the environment (Popen semantics), don't merge: a var
        # the driver deleted after factory start must not leak into the
        # worker.
        os.environ.clear()
        os.environ.update({k: str(v) for k, v in env.items()})
        # Back-channel to the template (argv survives the fork): a
        # worker that ends up importing jax touches this marker so the
        # template upgrades itself for future forks (two-stage boot).
        if len(sys.argv) > 1:
            os.environ["RAY_TPU_FACTORY_MARKER"] = os.path.join(
                os.path.dirname(sys.argv[1]), JAX_MARKER)
        # The Popen path hands PYTHONPATH to a fresh interpreter; a fork
        # must apply it by hand (and pip/conda runtime envs layer their
        # site-packages the same way at task level).
        for p in reversed(env.get("PYTHONPATH", "").split(os.pathsep)):
            if p and p not in sys.path:
                sys.path.insert(0, p)
        if req.get("cwd"):
            try:
                os.chdir(req["cwd"])
            except OSError:
                pass  # missing cwd: worker runs where it can
        log_path = req.get("log_path")
        if log_path:
            fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
        else:
            fd = os.open(os.devnull, os.O_WRONLY)
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        os.close(fd)
        from ray_tpu._private.worker_pool import worker_main

        os.environ.pop("RAY_TPU_WORKER_AUTHKEY", None)
        if pipe_fd is not None:
            # Kernel-passed socketpair end (SCM_RIGHTS through the
            # factory): possession IS the authentication — no listener
            # accept, no HMAC challenge round-trips.
            from multiprocessing.connection import Connection

            conn = Connection(pipe_fd)
        else:
            from multiprocessing.connection import Client

            authkey = bytes.fromhex(req["authkey"])
            conn = Client(req["addr"], family="AF_UNIX",
                          authkey=authkey)
        worker_main(conn)
        rc = 0
    except BaseException:  # noqa: BLE001 — log to the worker's own log
        import traceback

        traceback.print_exc()
    finally:
        os._exit(rc)


def _freeze_heap() -> None:
    # Freeze the template heap: everything imported so far moves to the
    # permanent generation, so the CHILDREN's garbage collector never
    # scans (and copy-on-write-faults) those pages. Without this every
    # fork pays ~tens of ms of CoW churn the moment its first gc cycle
    # walks the inherited jax/numpy object graph — at actor-creation
    # waves that churn IS the bottleneck on 1-core hosts.
    import gc

    gc.disable()
    gc.collect()
    gc.freeze()


JAX_MARKER = "jax_wanted"


def factory_main(sock_path: str, parent_pid: int) -> None:
    # Pre-import the worker stack ONCE; every fork shares these pages.
    # Workers are CPU processes (the daemon owns the TPU), so importing
    # jax here is safe and saves each fork its heaviest import. But the
    # jax import is ~3x the rest of the template boot, and a fleet of
    # daemons booting factories serializes those imports on the host's
    # cores right when an actor/task wave needs them — so the default
    # is a TWO-STAGE boot: come up with only the worker stack + numpy
    # (fast READY, cheap-but-jaxless forks) and import jax later, the
    # first time a forked worker actually pulls jax in. The children
    # can't message us over the spawn socket (they hold no client), so
    # the signal is a marker file in the socket's private 0700 dir:
    # forks inherit this argv, notice 'jax' landing in their
    # sys.modules, and touch it; we poll it from the accept loop and
    # upgrade between spawn requests.
    #   RAY_TPU_FACTORY_JAX=eager restores the old import-at-boot
    #   behaviour; RAY_TPU_FACTORY_LEAN=1 (or FACTORY_JAX=off) never
    #   imports jax into the template at all.
    import ray_tpu._private.worker_pool  # noqa: F401

    mode = os.environ.get("RAY_TPU_FACTORY_JAX", "auto").lower()
    if os.environ.get("RAY_TPU_FACTORY_LEAN",
                      "0").lower() not in ("", "0", "false", "no"):
        mode = "off"
    jax_loaded = False
    if mode == "eager":
        try:
            import jax  # noqa: F401

            jax_loaded = True
        except Exception:  # noqa: BLE001 — workers will import lazily
            pass
    elif mode != "off":
        try:
            # Everything a non-jax worker touches on its first task,
            # so stage-one forks are as cheap as fully-warmed ones:
            # numpy (result packing, user arrays) plus the worker-side
            # runtime modules and their stdlib closure (measured as the
            # sys.modules delta of a fresh fork's first no-op task).
            import multiprocessing.connection  # noqa: F401
            import pathlib  # noqa: F401
            import shutil  # noqa: F401
            import tempfile  # noqa: F401
            import zipfile  # noqa: F401

            import numpy  # noqa: F401

            import ray_tpu._private.rpc  # noqa: F401
            import ray_tpu._private.runtime_env_packaging  # noqa: F401
            import ray_tpu._private.worker_client  # noqa: F401
        except Exception:  # noqa: BLE001
            pass
    _freeze_heap()
    marker_path = os.path.join(os.path.dirname(sock_path), JAX_MARKER)

    def _maybe_upgrade() -> None:
        nonlocal jax_loaded
        if jax_loaded or mode in ("off", "eager"):
            return
        if not os.path.exists(marker_path):
            return
        try:
            import jax  # noqa: F401
        except Exception:  # noqa: BLE001 — keep serving lean forks
            pass
        jax_loaded = True  # don't re-attempt either way
        _freeze_heap()

    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    server.bind(sock_path)
    server.listen(64)
    server.settimeout(1.0)
    # Readiness handshake: the parent waits for this byte so the first
    # spawn request never races the bind.
    print("FACTORY_READY", flush=True)
    while True:
        # Reap finished workers (they are OUR children post-fork).
        try:
            while os.waitpid(-1, os.WNOHANG)[0] != 0:
                pass
        except ChildProcessError:
            pass
        if os.getppid() != parent_pid:
            break  # daemon died; orphaned factory must not linger
        try:
            conn, _ = server.accept()
        except socket.timeout:
            # Idle moment: safe to pay the ~0.5s jax import without
            # stalling a queued spawn request.
            _maybe_upgrade()
            continue
        except OSError:
            break
        pipe_fd: int | None = None
        try:
            # Every request starts with a 2-byte marker; b"FD" carries
            # the worker's pre-connected pipe end as ancillary data.
            marker, fds, _, _ = socket.recv_fds(conn, 2, 1)
            while len(marker) < 2:
                more = conn.recv(2 - len(marker))
                if not more:
                    raise EOFError("factory peer closed")
                marker += more
            if marker == b"FD" and fds:
                pipe_fd = fds[0]
            req = _recv_msg(conn)
            if req.get("op") == "exit":
                _send_msg(conn, {"ok": True})
                break
            pid = os.fork()
            if pid == 0:
                server.close()
                conn.close()
                _child_exec(req, pipe_fd)  # never returns
            _send_msg(conn, {"ok": True, "pid": pid})
        except BaseException as exc:  # noqa: BLE001 — keep serving
            try:
                _send_msg(conn, {"ok": False, "error": repr(exc)})
            except OSError:
                pass  # requester hung up before the reply
        finally:
            if pipe_fd is not None:
                try:
                    os.close(pipe_fd)  # the child inherited its copy
                except OSError:
                    pass  # child inherited the fd; ours may be gone
            try:
                conn.close()
            except OSError:
                pass  # requester already hung up
    try:
        server.close()
        os.unlink(sock_path)
    except OSError:
        pass  # socket path already removed


# --------------------------------------------------------------------------
# Client side (runs in the daemon/driver process)
# --------------------------------------------------------------------------


class PidHandle:
    """Popen-compatible handle for a process that is NOT our child (the
    factory's child). Liveness and signalling go through a pidfd
    (os.pidfd_open), which is immune to PID recycling: after the
    factory reaps the worker and the kernel reuses the PID, signal-0
    liveness would report an unrelated process as 'our worker' and
    terminate()/kill() could hit an innocent bystander. The pidfd
    stays bound to the original process forever (readable once it
    exits) regardless of reaping or reuse."""

    def __init__(self, pid: int):
        self.pid = pid
        self._rc: int | None = None
        self._pidfd: int | None = -1  # sentinel: kill(pid, 0) fallback
        try:
            self._pidfd = os.pidfd_open(pid)
        except ProcessLookupError:
            # Genuinely gone (exited and reaped before we got here).
            self._pidfd = None
            self._rc = -1
        except (OSError, AttributeError):
            # No pidfd support (kernel < 5.3, seccomp EPERM/ENOSYS, or
            # no os.pidfd_open at all): the worker is LIVE — fall back
            # to signal-0 liveness, imperfect but never dead-on-arrival.
            self._pidfd = -1

    def __del__(self):
        if self._pidfd is not None and self._pidfd >= 0:
            try:
                os.close(self._pidfd)
            except OSError:
                pass  # pidfd already closed at GC

    def poll(self) -> int | None:
        if self._rc is not None:
            return self._rc
        if self._pidfd == -1:  # no-pidfd fallback
            try:
                os.kill(self.pid, 0)
                return None
            except ProcessLookupError:
                self._rc = -1
                return self._rc
            except PermissionError:
                return None
        import select

        readable, _, _ = select.select([self._pidfd], [], [], 0)
        if readable:
            self._rc = self._exit_status()
        return self._rc

    def _exit_status(self) -> int:
        """Recover the worker's REAL exit status where the kernel
        allows it: waitid(P_PIDFD, WEXITED|WNOWAIT) reads the status
        without consuming it (the factory is the reaping parent, and
        on same-process children a later wait must still succeed).
        Falls back to -1 — 'exited, status unknown' — when the kernel
        predates P_PIDFD or the process was already reaped by the
        factory (waitid is parent-only)."""
        try:
            p_pidfd = os.P_PIDFD  # Python 3.9+/Linux 5.4+
        except AttributeError:
            return -1
        try:
            res = os.waitid(p_pidfd, self._pidfd,
                            os.WEXITED | os.WNOWAIT | os.WNOHANG)
        except (ChildProcessError, OSError):
            return -1  # not our child / already reaped
        if res is None:
            return -1  # raced: readable but not yet waitable
        if res.si_code == os.CLD_EXITED:
            return res.si_status
        # Killed by signal: report the negated signal number, matching
        # subprocess.Popen.returncode semantics.
        return -res.si_status

    def wait(self, timeout: float | None = None) -> int:
        import subprocess

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    f"worker-{self.pid}", timeout)
            time.sleep(0.02)

    def _signal(self, sig: int) -> None:
        import signal as signal_mod

        try:
            if self._pidfd is not None and self._pidfd >= 0:
                signal_mod.pidfd_send_signal(self._pidfd, sig)
            elif self._pidfd == -1:
                os.kill(self.pid, sig)
        except OSError:
            pass  # process already reaped

    def terminate(self) -> None:
        import signal

        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        import signal

        self._signal(signal.SIGKILL)


# Env vars read at jax/XLA IMPORT time: the template has already
# imported jax, so a fork can't honor a different value — such workers
# must take the fresh-interpreter path.
_IMPORT_SENSITIVE_PREFIXES = ("JAX_", "XLA_", "LIBTPU", "TPU_",
                              "PYTHONHASHSEED")


def import_sensitive_subset(env: dict) -> dict:
    return {k: v for k, v in env.items()
            if k.startswith(_IMPORT_SENSITIVE_PREFIXES)}


class WorkerFactory:
    """Handle to a running factory process; ``spawn`` forks one worker."""

    def __init__(self, proc, sock_path: str, baseline_env: dict):
        self.proc = proc
        self.sock_path = sock_path
        # The import-time-sensitive env the template booted with; spawn
        # requests demanding a different one cannot be served by fork.
        self.baseline_sensitive = import_sensitive_subset(baseline_env)

    def compatible(self, env: dict) -> bool:
        return import_sensitive_subset(env) == self.baseline_sensitive

    def spawn(self, *, addr: str | None = None,
              authkey_hex: str | None = None, env: dict,
              cwd: str | None, log_path: str | None,
              pipe_fd: int | None = None,
              timeout_s: float = 20.0) -> PidHandle:
        """Fork one worker. ``pipe_fd`` (preferred) ships a connected
        socketpair end to the child over SCM_RIGHTS — no listener
        accept or auth handshake; ``addr``/``authkey_hex`` keep the
        connect-back path for callers without fd passing."""
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(timeout_s)
        try:
            conn.connect(self.sock_path)
            if pipe_fd is not None:
                socket.send_fds(conn, [b"FD"], [pipe_fd])
            else:
                conn.sendall(b"NO")
            _send_msg(conn, {"op": "spawn", "addr": addr,
                             "authkey": authkey_hex, "env": env,
                             "cwd": cwd, "log_path": log_path})
            reply = _recv_msg(conn)
        finally:
            conn.close()
        if not reply.get("ok"):
            raise RuntimeError(
                f"worker factory spawn failed: {reply.get('error')}")
        return PidHandle(reply["pid"])

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self) -> None:
        try:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(2.0)
            conn.connect(self.sock_path)
            conn.sendall(b"NO")  # marker: no fd rides this request
            _send_msg(conn, {"op": "exit"})
            conn.close()
        except OSError:
            pass  # factory already exited
        try:
            self.proc.wait(timeout=2.0)
        except Exception:  # noqa: BLE001
            try:
                self.proc.kill()
            except OSError:
                pass  # process exited between wait and kill


def start_factory(timeout_s: float | None = None) -> WorkerFactory:
    """Launch the template process for THIS process's workers. The
    template boots with the same CPU-pinned env as Popen'd workers."""
    import subprocess
    import tempfile

    if timeout_s is None:
        # Many daemons booting factories at once (single-box clusters)
        # serialize on the host's cores; honor the same knob that
        # governs worker startup so load-tuning covers both.
        from ray_tpu._private.config import GLOBAL_CONFIG

        timeout_s = max(
            60.0, float(GLOBAL_CONFIG.worker_startup_timeout_s) * 2)
    sock_dir = tempfile.mkdtemp(prefix="ray_tpu_factory_")
    os.chmod(sock_dir, 0o700)
    sock_path = os.path.join(sock_dir, "factory.sock")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["RAY_TPU_SKIP_TPU_DETECTION"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in sys.path if p] +
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    env.pop("RAY_TPU_WORKER_FACTORY_DISABLE", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.worker_factory",
         sock_path, str(os.getpid())],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + timeout_s
    line = b""
    os.set_blocking(proc.stdout.fileno(), False)
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError("worker factory exited during startup")
        try:
            chunk = proc.stdout.read()
        except OSError:
            chunk = None
        if chunk:
            line += chunk
        if b"FACTORY_READY" in line:
            return WorkerFactory(proc, sock_path, env)
        time.sleep(0.02)
    proc.kill()
    raise RuntimeError("worker factory never became ready")


if __name__ == "__main__":
    factory_main(sys.argv[1], int(sys.argv[2]))
