"""Sharded GCS hot tables: N in-head shard domains behind ``gcs_shards``.

Reference intent: the reference paper's sharded GCS (the control-plane
tables are partitioned by key so the store scales and no single table
loss takes the cluster down). Here the shards stay IN the head process
— the win this PR cashes in is fault isolation and lock-domain
parallelism, not multi-host placement:

- A stable CRC32 router (``shard_of``) sends every node / object /
  task id to its owning shard. CRC32 over the raw bytes is deliberate:
  Python's ``hash()`` is salted per process, and a router that moves
  keys across restarts would silently misroute the restored directory.
- Each shard owns its own lock domain (``gcs_shard.ShardState<i>`` /
  ``gcs_shard.NodeStatsShard<i>`` / ``gcs_shard.TaskEventShard<i>``
  lock_witness classes), its own "RGW1"-framed WAL + snapshot segment
  (``<snapshot>.shard<i>`` / ``<snapshot>.shard<i>.wal``) and its own
  persisted incarnation epoch (``gcs_epoch_shard<i>``), so one shard
  crash-restarts independently — replaying only its WAL, fencing its
  stale writers typed via the existing ``StaleEpochError`` machinery —
  while the other shards keep serving.
- Degraded mode: a stalled/partitioned shard serves its stale
  in-memory view (``age_s`` exposed in the stats row) and queues
  writes — WAL-durable at enqueue time, so an acked write survives
  even a crash during the stall — shedding ``SystemOverloadedError``
  typed past ``gcs_shard_max_queued_writes``: never hang, never lose
  an acked write.
- Resharding an existing layout is refused typed (``ReshardError``,
  gcs_persistence.py): a changed ring over persisted segments would
  be a silent full-directory misroute.

Disarmed (``gcs_shards=1``, the default) the head keeps the PR 12
single-snapshot+WAL layout byte-identically; ``SHARDS_ON`` is the
disarm gate the analysis pass tracks.
"""

from __future__ import annotations

import os
import pickle
import time
import zlib

from ray_tpu._private import flight_recorder, lock_witness
from ray_tpu._private import gcs_persistence as gp

# Disarm gate for the `gcs_shards` knob (disarm-gates pass): armed by
# the head/GCS boot via init_from_config(); hot paths branch on the
# shard state captured at construction, construction branches on this.
SHARDS_ON: bool = False
_SHARD_COUNT: int = 1

_MB = 1024 * 1024

# Per-shard stats registry (counter-keys pass): ShardState.stats() is
# the builder; metrics_agent.py exports each key as one
# ray_tpu_gcs_shard{shard=,key=} gauge sample.
GCS_SHARD_STAT_KEYS = (
    "epoch",
    "wal_records_written",
    "wal_records_replayed",
    "snapshots_written",
    "restores",
    "fenced_writes",
    "queued_writes",
    "shed_writes",
    "age_s",
)


def init_from_config() -> int:
    """One-time arming read at head/GCS construction: latch the
    configured shard count and flip the gate."""
    global SHARDS_ON, _SHARD_COUNT
    from ray_tpu._private.config import GLOBAL_CONFIG

    count = max(1, int(GLOBAL_CONFIG.gcs_shards))
    _SHARD_COUNT = count
    SHARDS_ON = count > 1
    return count


def shard_count() -> int:
    return _SHARD_COUNT


def shard_of(key: str, count: int | None = None) -> int:
    """Stable router: id hex / owner string -> shard index. Same id,
    same shard, every process and every incarnation."""
    if count is None:
        count = _SHARD_COUNT
    if count <= 1:
        return 0
    return zlib.crc32(key.encode()) % count


def apply_dir_op(directory, op: tuple):
    """Apply one WAL'd directory op to a shard's ObjectDirectory.
    Restore replay and the degraded-mode queue drain share this
    dispatch (the caller detaches/never-attached the WAL hook, so an
    already-durable op is not re-framed)."""
    kind = op[0]
    if kind == "dir_update":
        return directory.update(op[1], op[2], op[3])
    if kind == "dir_spill":
        return directory.mark_spilled(op[1], op[2], op[3])
    if kind == "dir_unspill":
        return directory.clear_spilled(op[1], op[2])
    if kind == "dir_prune_node":
        return directory.prune_node(op[1])
    raise ValueError(f"unknown shard wal op {kind!r}")


class NodeStatsShard:
    """One shard's slice of the heartbeat-piggybacked node-stats table:
    its own lock domain so record_node_stats lands without a
    global-lock pass. Volatile — repopulated by the next heartbeat."""

    def __init__(self, index: int):
        self.index = index
        self.lock = lock_witness.Lock(f"gcs_shard.NodeStatsShard{index}")
        # node hex -> (stats dict, monotonic received-at)
        self.rows: dict = {}


class TaskEventShard:
    """One shard's slice of the bounded task-event table (events,
    group markers and the per-shard drop counter). Volatile."""

    def __init__(self, index: int, limit: int):
        self.index = index
        self.lock = lock_witness.Lock(f"gcs_shard.TaskEventShard{index}")
        self.events: dict = {}
        self.groups: dict = {}
        self.group_entries = 0
        self.dropped = 0
        self.limit = limit


class ShardState:
    """One in-head shard domain: its slice of the object directory plus
    its own lock domain, WAL + snapshot segment, persisted incarnation
    epoch, and the degraded-mode (stall) write queue. gcs_server.py
    routes ops here and owns fencing/chaos; this class owns the
    mechanics."""

    def __init__(self, index: int, count: int, persist_path: str, *,
                 fsync: bool = False, queue_cap: int = 512):
        from ray_tpu._private.gcs import ObjectDirectory

        self.index = index
        self.count = count
        self.snap_path = f"{persist_path}.shard{index}"
        self.wal_path = f"{persist_path}.shard{index}.wal"
        base_dir = os.path.dirname(persist_path) or "."
        self.epoch_path = os.path.join(base_dir, f"gcs_epoch_shard{index}")
        self.fsync = fsync
        self.queue_cap = queue_cap
        # Every shard is its own lock-witness class: a cross-shard
        # ordering mistake shows up as a witnessed cycle, not a
        # once-a-month deadlock.
        self.lock = lock_witness.Lock(f"gcs_shard.ShardState{index}")
        self.directory = ObjectDirectory()
        self.on_persist_error = None  # set by gcs_server: shared backoff
        self.epoch = 0
        self.wal = None
        self.wal_seq = 0
        self.persisted_version = -1
        self.last_snapshot_at = 0.0
        self.wal_records_written = 0
        self.wal_records_replayed = 0
        self.snapshots_written = 0
        self.restores = 0
        self.fenced_writes = 0
        self.shed_writes = 0
        self.stalled_until = 0.0
        self.stalled_since = 0.0
        self._queue: list = []

    # ------------------------------------------------------ persistence

    def boot(self) -> int:
        """First start of this head incarnation: mint the shard epoch,
        restore this shard's snapshot + WAL segment ONLY, then open the
        WAL and hook the directory's mutation stream into it."""
        with self.lock:
            self.epoch = gp.mint_epoch(self.epoch_path)
            replayed = self._restore_locked()
            self._open_wal_locked()
            return replayed

    def crash_restart(self, reason: str) -> int:
        """Shard crash + independent recovery: drop the in-memory
        domain, mint the NEXT persisted shard epoch (the fencing token
        — stale writers get typed StaleEpochError), rebuild from this
        shard's segment. Queued degraded-mode writes are already
        WAL-durable; the replay here is what keeps their acks honest."""
        from ray_tpu._private.gcs import ObjectDirectory

        with self.lock:
            if self.wal is not None:
                self.wal.close()
                self.wal = None
            self._queue = []
            self.stalled_until = 0.0
            self.stalled_since = 0.0
            self.directory = ObjectDirectory()
            self.persisted_version = -1
            self.epoch = gp.mint_epoch(self.epoch_path)
            replayed = self._restore_locked()
            self._open_wal_locked()
            self.restores += 1
        flight_recorder.record("gcs.shard_restore", self.index, replayed,
                               reason)
        return replayed

    def _restore_locked(self) -> int:
        state = None
        for path in (self.snap_path, f"{self.snap_path}.prev"):
            try:
                state = pickle.loads(gp.read_snapshot(path))
                break
            except FileNotFoundError:
                continue
            except (gp.TornSnapshotError, gp.LegacySnapshotError,
                    OSError, EOFError, pickle.UnpicklingError):
                # Torn/unreadable shard snapshot: reject-don't-crash —
                # flight-record it and fall back to .prev + WAL replay
                # (same discipline as the head's full snapshot).
                flight_recorder.record("gcs.torn_snapshot", path,
                                       self.index)
                continue
        base_seq = 0
        if state is not None:
            recorded = int(state.get("gcs_shards", 0))
            if recorded != self.count:
                raise gp.ReshardError(recorded, self.count)
            base_seq = int(state.get("wal_seq", 0))
            self.directory.restore_state(state.get("directory") or {})
        replayed = 0
        last_seq = base_seq
        for wal_path in (f"{self.wal_path}.prev", self.wal_path):
            stats = gp.replay_wal(
                wal_path, base_seq,
                lambda op: apply_dir_op(self.directory, op))
            replayed += stats["replayed"]
            last_seq = max(last_seq, stats["last_seq"])
        self.wal_seq = last_seq
        self.wal_records_replayed += replayed
        return replayed

    def _open_wal_locked(self) -> None:
        self.wal = gp.WalWriter(self.wal_path, fsync=self.fsync)
        self.directory.wal_emit = self._wal_append

    def _wal_append(self, op: tuple) -> None:
        # Reached via ObjectDirectory._mutated with this shard's lock
        # held (every shard mutation funnels through gcs_server under
        # self.lock), so the seq is single-writer by construction.
        if self.wal is None:
            return
        self.wal_seq += 1
        try:
            self.wal.append(self.wal_seq,
                            pickle.dumps(op, pickle.HIGHEST_PROTOCOL))
        except OSError:
            if self.on_persist_error is not None:
                self.on_persist_error(f"shard{self.index}_wal")
            return
        self.wal_records_written += 1

    def maybe_snapshot(self, interval_s: float, max_wal_mb: float,
                       fsync: bool, force: bool = False) -> bool:
        """Periodic per-shard snapshot + WAL rotate (the head's persist
        tick fans out here). A wedged (stalled) domain is skipped —
        its durability rides the WAL until it heals."""
        now = time.monotonic()
        with self.lock:
            if self._stall_active_locked():
                return False
            wal_over = (self.wal is not None
                        and self.wal.size() > max_wal_mb * _MB)
            if not force and not wal_over \
                    and now - self.last_snapshot_at < interval_s:
                return False
            version = self.directory.version
            if not force and not wal_over \
                    and version == self.persisted_version:
                self.last_snapshot_at = now
                return False
            state = {
                "format": 1,
                "shard": self.index,
                "gcs_shards": self.count,
                "wal_seq": self.wal_seq,
                "epoch": self.epoch,
                "directory": self.directory.snapshot_state(),
            }
            payload = pickle.dumps(state, pickle.HIGHEST_PROTOCOL)
            try:
                gp.write_snapshot(self.snap_path, payload, fsync=fsync)
                if self.wal is not None:
                    self.wal.rotate()
            except OSError:
                if self.on_persist_error is not None:
                    self.on_persist_error(f"shard{self.index}_snapshot")
                return False
            self.persisted_version = version
            self.last_snapshot_at = now
            self.snapshots_written += 1
            return True

    def close(self) -> None:
        with self.lock:
            self._drain_locked()
            if self.wal is not None:
                self.wal.close()
                self.wal = None
            self.directory.wal_emit = None

    # ---------------------------------------------------- degraded mode

    def stall(self, duration_s: float) -> None:
        """Open (or extend) this shard's degraded window: reads keep
        serving the stale view, writes queue WAL-first."""
        with self.lock:
            now = time.monotonic()
            if now >= self.stalled_until:
                self.stalled_since = now
            self.stalled_until = max(self.stalled_until, now + duration_s)

    def stall_active(self) -> bool:
        with self.lock:
            return self._stall_active_locked()

    def _stall_active_locked(self) -> bool:
        # Heals lazily: the first check past the deadline drains the
        # queued writes into the live tables (ops already WAL'd, so the
        # emit hook is detached during the drain).
        if self.stalled_until <= 0.0:
            return False
        if time.monotonic() < self.stalled_until:
            return True
        self._drain_locked()
        self.stalled_until = 0.0
        self.stalled_since = 0.0
        return False

    def heal_tick(self) -> None:
        """Monitor-thread hook: bound post-stall staleness to one tick
        instead of waiting for the next write to trigger the drain."""
        with self.lock:
            self._stall_active_locked()

    def enqueue_locked(self, op: tuple) -> None:
        """Degraded-mode write (caller holds self.lock): WAL it NOW —
        the ack must survive even a crash during the stall — and queue
        the in-memory apply for heal. Past the cap the write sheds
        typed: never hang, never queue unboundedly, never drop an ack."""
        from ray_tpu.exceptions import SystemOverloadedError

        if len(self._queue) >= self.queue_cap:
            self.shed_writes += 1
            flight_recorder.record("gcs.shard_backoff", self.index,
                                   "shed", len(self._queue))
            raise SystemOverloadedError(
                f"gcs shard {self.index} degraded: "
                f"{len(self._queue)} queued writes at cap",
                retry_after_s=max(
                    0.1, self.stalled_until - time.monotonic()))
        self._wal_append(op)
        self._queue.append(op)
        flight_recorder.record("gcs.shard_backoff", self.index,
                               len(self._queue))

    def queue_len(self) -> int:
        with self.lock:
            return len(self._queue)

    def _drain_locked(self) -> None:
        if not self._queue:
            return
        ops, self._queue = self._queue, []
        emit = self.directory.wal_emit
        self.directory.wal_emit = None
        try:
            for op in ops:
                apply_dir_op(self.directory, op)
        finally:
            self.directory.wal_emit = emit

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        """One shard's live GCS_SHARD_STAT_KEYS row (the counter-keys
        pass holds this dict literal and the registry together)."""
        with self.lock:
            now = time.monotonic()
            stalled = 0.0 < now < self.stalled_until
            return {
                "epoch": self.epoch,
                "wal_records_written": self.wal_records_written,
                "wal_records_replayed": self.wal_records_replayed,
                "snapshots_written": self.snapshots_written,
                "restores": self.restores,
                "fenced_writes": self.fenced_writes,
                "queued_writes": len(self._queue),
                "shed_writes": self.shed_writes,
                "age_s": (now - self.stalled_since) if stalled else 0.0,
            }
