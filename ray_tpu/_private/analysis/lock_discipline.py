"""Pass ``lock-discipline``: a field guarded in one method is not
touched bare in another.

For every class the pass finds its lock attributes (``self.X``
assigned from ``threading.Lock/RLock/Condition`` or the
``lock_witness`` factories), then tracks which ``self.<field>``
accesses happen inside a ``with self.X:`` block (or in a method that
manually calls ``self.X.acquire()`` — conservatively treated as
guarded throughout, since block extent is not statically knowable).

A finding is a field that is WRITTEN under a lock in one method and
written bare in a different method. The deliberately-conservative
scope keeps the signal honest on this codebase's idioms:

- ``__init__`` (and other pre-publication constructors named
  ``_init*``) is exempt: objects under construction have no
  concurrent readers;
- methods whose name ends with ``_locked`` are treated as guarded —
  the caller holds the lock by naming convention;
- bare READS are not flagged: the runtime's hot paths read shared
  counters and tables lock-free by design (GIL-atomic loads, memo
  reads double-checked under the lock) and flagging every one would
  drown the writes that actually corrupt state;
- classes with no lock attribute are skipped — unlocked classes are
  single-threaded by contract, a different review.

Findings that survive triage as intentional (e.g. a monotonic counter
bumped bare on the hot path, summed under the lock only for stats)
get a suppression entry with the why.
"""

from __future__ import annotations

import ast

from ray_tpu._private.analysis import Finding

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
# Constructor-like methods whose bare writes are pre-publication.
_EXEMPT_METHODS = ("__init__", "__new__")


def _is_lock_ctor(node: ast.expr) -> bool:
    """``threading.Lock()`` / ``lock_witness.Condition(...)`` /
    ``threading.Condition(threading.Lock())`` shapes."""
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    name = func.attr if isinstance(func, ast.Attribute) \
        else func.id if isinstance(func, ast.Name) else None
    return name in _LOCK_FACTORIES


def _self_attr(node: ast.expr) -> "str | None":
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """One method body: which self-attrs are read/written, and under
    which held lock attrs."""

    def __init__(self, lock_attrs: "set[str]"):
        self.lock_attrs = lock_attrs
        self.held: list[str] = []
        self.manual_acquire = False
        # field -> list of (is_write, guarded, line)
        self.accesses: dict[str, list] = {}

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            ctx = item.context_expr
            attr = _self_attr(ctx)
            if attr in self.lock_attrs:
                acquired.append(attr)
        self.held.extend(acquired)
        # The context expressions themselves evaluate unguarded.
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and attr not in self.lock_attrs:
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.accesses.setdefault(attr, []).append(
                (is_write, bool(self.held), node.lineno))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        # ``self.x += 1`` parses its target as Store only; count it as
        # a write (it is also a read, but one site, one record).
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # ``self._lock.acquire()`` / ``.wait()``: block extent unknown
        # — treat the whole method as guarded (conservative: hides
        # bare accesses in such methods rather than inventing them).
        func = node.func
        if isinstance(func, ast.Attribute) \
                and func.attr in ("acquire", "wait", "wait_for") \
                and _self_attr(func.value) in self.lock_attrs:
            self.manual_acquire = True
        self.generic_visit(node)


def _scan_class(src, cls: ast.ClassDef) -> "list[Finding]":
    lock_attrs: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for target in node.targets:
                attr = _self_attr(target)
                if attr:
                    lock_attrs.add(attr)
    if not lock_attrs:
        return []

    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef,
                                 ast.AsyncFunctionDef))]
    # field -> {"guarded": [(method, line)], "bare": [(method, line)]}
    table: dict[str, dict] = {}
    for method in methods:
        scan = _MethodScan(lock_attrs)
        for stmt in method.body:
            scan.visit(stmt)
        exempt = method.name in _EXEMPT_METHODS \
            or method.name.startswith("_init")
        convention_guarded = method.name.endswith("_locked") \
            or scan.manual_acquire
        for field, hits in scan.accesses.items():
            rec = table.setdefault(field,
                                   {"guarded": [], "bare": []})
            for is_write, guarded, line in hits:
                if not is_write:
                    continue
                if guarded or convention_guarded:
                    rec["guarded"].append((method.name, line))
                elif not exempt:
                    rec["bare"].append((method.name, line))

    findings: list[Finding] = []
    for field, rec in sorted(table.items()):
        if not rec["guarded"] or not rec["bare"]:
            continue
        guarded_methods = {m for m, _ in rec["guarded"]}
        cross = [(m, ln) for m, ln in rec["bare"]
                 if m not in guarded_methods]
        if not cross:
            # Same-method mixes are usually check-then-lock staging on
            # locals; the cross-method writes are the corruption risk.
            continue
        method, line = cross[0]
        others = "".join(f", {m}:{ln}" for m, ln in cross[1:3])
        findings.append(Finding(
            "lock-discipline", src.rel, line,
            f"{cls.name}.{field}",
            f"{cls.name}.{field} is written under "
            f"{'/'.join(sorted(lock_attrs))} in "
            f"{', '.join(sorted(guarded_methods))} but written BARE "
            f"in {method}(){others} — take the lock or suppress with "
            f"the why"))
    return findings


def run(sources) -> "list[Finding]":
    findings: list[Finding] = []
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_scan_class(src, node))
    return findings
