"""AST invariant linter for the runtime's concurrency conventions.

The threaded core rests on prose conventions — lock discipline around
shared tables, one-module-attribute disarm gates, registered chaos
sites, ``*_STAT_KEYS`` counter registries, no silent exception
swallows. Each was guarded only by spot checks; this package turns
them into mechanical passes over the tree (pure-stdlib ``ast``, no
imports of the code under analysis) that a tier-1 test and the
``python -m ray_tpu.analysis`` CLI run with zero tolerance for
unsuppressed findings.

Passes (ids are the suppression-file keys):

- ``lock-discipline``  fields written under a class's ``with
  self._lock`` in one method must not be written bare in another
  (heuristic; see lock_discipline.py for the exact rules)
- ``chaos-sites``      every ``chaos.should("<site>")`` string is in
  chaos.py's ``SITES`` registry + docstring and exercised in tests/
- ``counter-keys``     every ``*_STAT_KEYS`` registry matches the
  stats dict its module actually builds, and its family is exported
  through metrics_agent.py
- ``disarm-gates``     every ``*_ON`` disarm gate is declared once at
  module level, actually branches somewhere, and hot paths never read
  the config knob where the gate exists
- ``swallows``         bare ``except:`` and pass-only broad handlers
  (Exception/BaseException/OSError) without a why-comment

Suppression file (``suppressions.txt`` next to this module)::

    <pass-id> <path>::<qualifier>  # why this finding is acceptable

Every entry needs the why-comment; the tier-1 gate caps the file at
25 entries so triage cannot rot into wholesale silencing. Stale
entries (matching no current finding) are reported so the file shrinks
as fixes land.
"""

from __future__ import annotations

import io
import os
import sys
import tokenize
from dataclasses import dataclass

# Suppression-file budget, enforced by the CLI and the tier-1 gate:
# past this, suppressing stops being triage.
MAX_SUPPRESSIONS = 25

PASS_IDS = ("lock-discipline", "chaos-sites", "counter-keys",
            "disarm-gates", "swallows")


@dataclass(frozen=True)
class Finding:
    pass_id: str
    path: str        # repo-relative, forward slashes
    line: int
    ident: str       # stable suppression qualifier (no line numbers)
    message: str

    @property
    def key(self) -> str:
        return f"{self.pass_id} {self.path}::{self.ident}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_id}] "
                f"{self.message}\n    suppress with: {self.key}")


class SourceFile:
    """One parsed module: source text, AST, and the set of line
    numbers carrying a comment (passes use comments as the in-place
    justification pragma — ast alone cannot see them)."""

    def __init__(self, path: str, rel: str):
        import ast

        self.path = path
        self.rel = rel
        self.text = open(path, encoding="utf-8").read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        self.comment_lines: set[int] = set()
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comment_lines.add(tok.start[0])
        except tokenize.TokenizeError:  # pragma: no cover — parse ok'd
            pass


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


def default_package_root() -> str:
    return os.path.join(repo_root(), "ray_tpu")


def iter_sources(package_root: str) -> "list[SourceFile]":
    out = []
    base = os.path.dirname(os.path.abspath(package_root.rstrip("/")))
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            out.append(SourceFile(path, rel))
    return out


# --------------------------------------------------------------------------
# Suppressions
# --------------------------------------------------------------------------


def suppressions_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "suppressions.txt")


@dataclass(frozen=True)
class Suppression:
    key: str      # "<pass-id> <path>::<qualifier>"
    why: str
    line: int


def load_suppressions(path: "str | None" = None
                      ) -> "tuple[list[Suppression], list[str]]":
    """Parse the suppression file. Returns (entries, format_errors) —
    an entry without a why-comment is a format error, not a working
    suppression."""
    path = path or suppressions_path()
    entries: list[Suppression] = []
    errors: list[str] = []
    try:
        raw = open(path, encoding="utf-8").read()
    except OSError:
        return entries, errors
    for lineno, line in enumerate(raw.splitlines(), 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        body, sep, why = stripped.partition("#")
        body = body.strip()
        why = why.strip()
        parts = body.split(None, 1)
        if len(parts) != 2 or parts[0] not in PASS_IDS \
                or "::" not in parts[1]:
            errors.append(
                f"suppressions.txt:{lineno}: malformed entry "
                f"{stripped!r} (want '<pass-id> <path>::<qualifier>"
                f"  # why')")
            continue
        if not sep or not why:
            errors.append(
                f"suppressions.txt:{lineno}: entry {body!r} has no "
                f"why-comment — every suppression carries its triage "
                f"rationale")
            continue
        entries.append(Suppression(key=f"{parts[0]} {parts[1]}",
                                   why=why, line=lineno))
    return entries, errors


# --------------------------------------------------------------------------
# Driver
# --------------------------------------------------------------------------


def run_passes(package_root: "str | None" = None,
               pass_ids: "tuple[str, ...] | None" = None
               ) -> "list[Finding]":
    """Run the selected passes over the tree; returns RAW findings
    (suppressions not yet applied)."""
    from ray_tpu._private.analysis import (
        chaos_sites,
        counter_keys,
        disarm_gates,
        lock_discipline,
        swallows,
    )

    package_root = package_root or default_package_root()
    selected = pass_ids or PASS_IDS
    sources = iter_sources(package_root)
    registry = {
        "lock-discipline": lock_discipline.run,
        "chaos-sites": chaos_sites.run,
        "counter-keys": counter_keys.run,
        "disarm-gates": disarm_gates.run,
        "swallows": swallows.run,
    }
    findings: list[Finding] = []
    for pass_id in selected:
        findings.extend(registry[pass_id](sources))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return findings


def apply_suppressions(findings: "list[Finding]",
                       entries: "list[Suppression]"
                       ) -> "tuple[list[Finding], list[Suppression]]":
    """Split raw findings against the suppression entries. Returns
    (unsuppressed findings, stale entries that matched nothing)."""
    by_key = {e.key: e for e in entries}
    used: set[str] = set()
    open_findings = []
    for finding in findings:
        if finding.key in by_key:
            used.add(finding.key)
        else:
            open_findings.append(finding)
    stale = [e for e in entries if e.key not in used]
    return open_findings, stale


def main(argv: "list[str] | None" = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.analysis",
        description="AST invariant linter for the ray_tpu runtime "
                    "(lock discipline, chaos sites, counter keys, "
                    "disarm gates, exception swallows).")
    parser.add_argument("passes", nargs="*",
                        help=f"passes to run (default: all of "
                             f"{', '.join(PASS_IDS)})")
    parser.add_argument("--root", default=None,
                        help="package root to analyze (default: the "
                             "ray_tpu/ tree this module lives in)")
    parser.add_argument("--list-passes", action="store_true",
                        help="list pass ids and exit")
    parser.add_argument("--no-suppressions", action="store_true",
                        help="report raw findings, ignoring "
                             "suppressions.txt")
    parser.add_argument("--strict", action="store_true",
                        help="also fail on stale suppression entries")
    args = parser.parse_args(argv)

    if args.list_passes:
        for pass_id in PASS_IDS:
            print(pass_id)
        return 0
    for pass_id in args.passes:
        if pass_id not in PASS_IDS:
            print(f"unknown pass {pass_id!r}; valid: "
                  f"{', '.join(PASS_IDS)}", file=sys.stderr)
            return 2

    findings = run_passes(args.root,
                          tuple(args.passes) or None)
    entries, format_errors = ([], []) if args.no_suppressions \
        else load_suppressions()
    for err in format_errors:
        print(err, file=sys.stderr)
    open_findings, stale = apply_suppressions(findings, entries)

    for finding in open_findings:
        print(finding.render())
    for entry in stale:
        print(f"suppressions.txt:{entry.line}: stale entry (matches "
              f"no current finding): {entry.key}",
              file=sys.stderr)
    over_budget = len(entries) > MAX_SUPPRESSIONS
    if over_budget:
        print(f"suppressions.txt carries {len(entries)} entries — "
              f"over the {MAX_SUPPRESSIONS}-entry triage budget",
              file=sys.stderr)

    suppressed = len(findings) - len(open_findings)
    print(f"{len(open_findings)} finding(s) "
          f"({suppressed} suppressed, {len(stale)} stale "
          f"suppression(s))", file=sys.stderr)
    if open_findings or format_errors or over_budget:
        return 1
    if args.strict and stale:
        return 1
    return 0
