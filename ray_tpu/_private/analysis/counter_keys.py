"""Pass ``counter-keys``: ``*_STAT_KEYS`` registries cannot drift.

A counter key is a three-way contract: the module increments it, its
``*_STAT_KEYS`` registry names it (tests and the README doc-drift
check read the registry), and metrics_agent.py exports its family.
This pass enforces all three from the AST:

- every module-level ``*_STAT_KEYS`` tuple is matched against the
  stats dicts its own module builds (dict literals, ``d["k"] = ...``
  follow-up assignments, and ``{k: 0 for k in REGISTRY}`` seeding);
  a registry key no builder emits, or an emitted key missing from the
  registry, is a finding;
- the registry's per-node family (``ray_tpu_node_<group>``) must
  appear in metrics_agent.py, so heartbeat-shipped counters actually
  reach ``/metrics``.

Derived non-counter fields a stats dict carries alongside the
registry (gauges like ``restore_p50_ms``) are expected findings —
they live in the suppression file with their why.
"""

from __future__ import annotations

import ast

from ray_tpu._private.analysis import Finding

METRICS_AGENT_REL = "ray_tpu/_private/metrics_agent.py"


def _registries(sources) -> "list[tuple[object, str, int, tuple]]":
    """[(source, registry name, line, keys)] for every module-level
    ``*_STAT_KEYS = ("...", ...)``."""
    out = []
    for src in sources:
        for node in src.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if not (isinstance(target, ast.Name)
                        and target.id.endswith("_STAT_KEYS")):
                    continue
                if isinstance(node.value, ast.Tuple):
                    keys = tuple(
                        elt.value for elt in node.value.elts
                        if isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str))
                    out.append((src, target.id, node.lineno, keys))
    return out


def registry_keys(module_rel_contains: str, registry_name: str,
                  sources=None) -> "tuple[str, ...]":
    """Parse one registry's keys from the AST (exported so
    tests/test_doc_drift.py asserts docs against the same parser the
    linter uses)."""
    if sources is None:
        from ray_tpu._private.analysis import (
            default_package_root,
            iter_sources,
        )

        sources = iter_sources(default_package_root())
    for src, name, _, keys in _registries(sources):
        if name == registry_name and module_rel_contains in src.rel:
            return keys
    return ()


def _function_key_sets(tree, registry_name: str
                       ) -> "list[tuple[str, set, bool]]":
    """[(func qualname, emitted string keys, seeded-from-registry)]
    per function: dict-literal keys + ``var["k"] =`` constants, and
    whether a ``{k: ... for k in REGISTRY}`` comprehension seeds it."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        keys: set = set()
        seeded = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                for key_node in sub.keys:
                    if isinstance(key_node, ast.Constant) \
                            and isinstance(key_node.value, str):
                        keys.add(key_node.value)
            elif isinstance(sub, ast.Assign):
                for target in sub.targets:
                    if isinstance(target, ast.Subscript) \
                            and isinstance(target.slice, ast.Constant) \
                            and isinstance(target.slice.value, str):
                        keys.add(target.slice.value)
            elif isinstance(sub, ast.DictComp):
                for gen in sub.generators:
                    it = gen.iter
                    if isinstance(it, ast.Name) \
                            and it.id == registry_name:
                        seeded = True
        if keys or seeded:
            out.append((node.name, keys, seeded))
    return out


def run(sources) -> "list[Finding]":
    findings: list[Finding] = []
    seen_idents: set = set()

    def emit(finding: Finding) -> None:
        if finding.ident not in seen_idents:
            seen_idents.add(finding.ident)
            findings.append(finding)

    metrics_text = ""
    for src in sources:
        if src.rel == METRICS_AGENT_REL:
            metrics_text = src.text

    for src, name, line, keys in _registries(sources):
        if not keys:
            emit(Finding("counter-keys", src.rel, line, name,
                         f"{name} registry is empty"))
            continue
        builders = _function_key_sets(src.tree, name)
        # Candidate stats builders: functions emitting at least half
        # of this registry's keys (or seeded straight from it).
        candidates = [(fn, ks, seeded) for fn, ks, seeded in builders
                      if seeded or len(ks & set(keys)) * 2 >= len(keys)]
        if not candidates:
            emit(Finding(
                "counter-keys", src.rel, line, f"{name}.builder",
                f"no stats builder in {src.rel} emits {name}'s keys — "
                f"the registry no longer matches any dict the module "
                f"returns"))
            continue
        emitted_anywhere: set = set()
        seeded_any = False
        for _, ks, seeded in candidates:
            emitted_anywhere |= ks
            seeded_any = seeded_any or seeded
        for key in keys:
            if key not in emitted_anywhere and not seeded_any:
                emit(Finding(
                    "counter-keys", src.rel, line, f"{name}.{key}",
                    f"registry key {key!r} ({name}) is never emitted "
                    f"by the module's stats builders — stale registry "
                    f"row"))
        for fn, ks, seeded in candidates:
            for key in sorted(ks - set(keys)):
                emit(Finding(
                    "counter-keys", src.rel, line,
                    f"{name}.{fn}.{key}",
                    f"{fn}() emits {key!r} next to the {name} "
                    f"counters but the key is not registered — add it "
                    f"to {name} (and a README row) or suppress with "
                    f"its why"))
        # Export check: the per-node family must exist in the agent.
        group = name[: -len("_STAT_KEYS")].lower()
        families = (f"ray_tpu_node_{group}", f"ray_tpu_node_{group}s")
        if metrics_text and not any(f in metrics_text
                                    for f in families):
            emit(Finding(
                "counter-keys", src.rel, line, f"{name}.family",
                f"{name} has no ray_tpu_node_{group} family in "
                f"metrics_agent.py — heartbeat-shipped counters never "
                f"reach /metrics"))
    return findings
