"""Pass ``swallows``: silent broad exception swallows.

The class of bug PR 12's persist path fixed: ``_save_snapshot`` ate
every OSError with a bare ``pass``, so a full disk silently disabled
durability. The rule:

- a bare ``except:`` is always a finding (even commented — name the
  exception);
- a handler whose body is ONLY ``pass``/``continue`` and whose type
  includes ``Exception``, ``BaseException`` or ``OSError`` (alone or
  in a tuple) is a finding UNLESS a comment on the handler's lines
  states why the swallow is safe — the comment is the in-place
  justification pragma, reviewed like any other code.

Narrow-typed swallows (``except queue.Empty: pass``,
``except FileNotFoundError: pass``) are idiomatic and not flagged.
"""

from __future__ import annotations

import ast

from ray_tpu._private.analysis import Finding

BROAD = {"Exception", "BaseException", "OSError"}


def _type_names(node: "ast.expr | None") -> "list[str]":
    if node is None:
        return []
    if isinstance(node, ast.Tuple):
        return [n for elt in node.elts for n in _type_names(elt)]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    if isinstance(node, ast.Name):
        return [node.id]
    return ["<expr>"]


def _qualifier(src, handler: ast.ExceptHandler) -> str:
    """Stable suppression ident: the enclosing def/class chain."""
    chain = []
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.lineno <= handler.lineno \
                    <= (node.end_lineno or node.lineno):
                chain.append((node.lineno, node.name))
    chain.sort()
    return ".".join(name for _, name in chain) or "<module>"


def run(sources) -> "list[Finding]":
    findings: list[Finding] = []
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            names = _type_names(node.type)
            bare = node.type is None
            pass_only = all(isinstance(stmt, (ast.Pass, ast.Continue))
                            for stmt in node.body)
            if bare:
                findings.append(Finding(
                    "swallows", src.rel, node.lineno,
                    f"{_qualifier(src, node)}:bare-except",
                    "bare `except:` — name the exception type(s) this "
                    "handler means to absorb"))
                continue
            if not pass_only or not (set(names) & BROAD):
                continue
            span = range(node.lineno,
                         (node.body[-1].end_lineno or node.lineno) + 1)
            if any(line in src.comment_lines for line in span):
                continue  # justified in place
            findings.append(Finding(
                "swallows", src.rel, node.lineno,
                f"{_qualifier(src, node)}:"
                f"{'-'.join(sorted(names))}",
                f"silent swallow of {'/'.join(sorted(names))} — "
                f"handle it (counter + flight_recorder), narrow the "
                f"type, or justify with a comment on the handler"))
    return findings
