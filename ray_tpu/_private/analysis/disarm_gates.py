"""Pass ``disarm-gates``: one module attribute per disarmed plane.

Every optional plane (tracing, perf, spill, fused execution, raw
framing, locality scheduling, speculation, chaos, lock witness) pays
for its disarmed state with exactly ONE module-attribute branch per
site — ``if perf_plane.PERF_ON:`` — never a per-hit config lookup
(``GLOBAL_CONFIG.x`` takes a lock and a dict probe per read). The
rules this pass enforces:

- a module-level ALL-CAPS ``*_ON`` assignment declares a gate; a gate
  name must be declared in exactly one module (two modules sharing
  ``PERF_ON`` would make ``from x import PERF_ON`` sites ambiguous);
- every declared gate is branched on somewhere in the tree (an
  unreferenced gate is a plane nothing can disarm);
- the plane's config knob is read ONLY in the gate's home module or
  inside init/boot/arming functions elsewhere — a knob read on a
  gated site means the site pays the config lock per hit;
- no single ``if`` test branches on two different gates (a site
  belongs to one plane; compound gating hides which knob disarms it).

``chaos.ACTIVE`` is grandfathered as the chaos plane's gate (the
``is not None`` idiom predates the ``*_ON`` convention).
"""

from __future__ import annotations

import ast

from ray_tpu._private.analysis import Finding

# knob in config._DEFAULTS -> (home module rel path, gate attribute).
KNOB_GATES: "dict[str, tuple[str, str]]" = {
    "tracing_enabled": ("ray_tpu/util/tracing.py", "TRACE_ON"),
    "perf_plane": ("ray_tpu/_private/perf_plane.py", "PERF_ON"),
    "spill_enabled": ("ray_tpu/_private/spill_manager.py", "SPILL_ON"),
    "fused_execution": ("ray_tpu/_private/node_executor.py",
                        "FUSED_ON"),
    "raw_framing": ("ray_tpu/_private/serialization.py", "RAW_ON"),
    "locality_aware_scheduling": ("ray_tpu/_private/scheduler.py",
                                  "LOCALITY_ON"),
    "speculation_enabled": ("ray_tpu/_private/speculation.py",
                            "SPEC_ON"),
    "lock_witness": ("ray_tpu/_private/lock_witness.py", "WITNESS_ON"),
    "driver_sharded_dispatch": ("ray_tpu/_private/dispatch_lanes.py",
                                "SHARD_ON"),
    "llm_paged_engine": ("ray_tpu/serve/llm_engine/engine.py",
                         "PAGED_ON"),
    "gcs_shards": ("ray_tpu/_private/gcs_shard.py", "SHARDS_ON"),
    "metrics_history": ("ray_tpu/_private/metrics_history.py",
                        "HISTORY_ON"),
    "chaos": ("ray_tpu/_private/chaos.py", "ACTIVE"),
}

# Functions allowed to read plane knobs outside the home module: the
# one-time arming/boot paths (Runtime init, daemon boot, module
# init_from_config hooks).
_ARMING_NAMES = ("init", "boot", "start", "enable", "arm",
                 "configure", "main", "_apply", "daemon", "run_")


def _gate_names() -> "set[str]":
    return {gate for _, gate in KNOB_GATES.values()}


def _declared_gates(sources) -> "dict[str, list[tuple[str, int]]]":
    """{gate name -> [(module rel, line)]} for module-level *_ON
    assignments."""
    out: dict[str, list[tuple[str, int]]] = {}
    for src in sources:
        for node in src.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for target in targets:
                if isinstance(target, ast.Name) \
                        and target.id.isupper() \
                        and target.id.endswith("_ON"):
                    out.setdefault(target.id, []).append(
                        (src.rel, node.lineno))
    return out


def _enclosing_funcs(tree) -> "list[tuple[int, int, str]]":
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.lineno, node.end_lineno or node.lineno,
                          node.name))
    return spans


def _in_arming_function(spans, lineno: int) -> bool:
    for start, end, name in spans:
        if start <= lineno <= end \
                and any(tag in name.lower() for tag in _ARMING_NAMES):
            return True
    return False


def run(sources) -> "list[Finding]":
    findings: list[Finding] = []
    declared = _declared_gates(sources)
    known_gates = _gate_names()

    # Duplicate declarations (one gate name, several modules).
    for gate, where in sorted(declared.items()):
        if len(where) > 1:
            paths = ", ".join(f"{p}:{ln}" for p, ln in where)
            for path, line in where:
                findings.append(Finding(
                    "disarm-gates", path, line, f"dup.{gate}",
                    f"disarm gate {gate!r} declared in multiple "
                    f"modules ({paths}) — one plane, one gate, one "
                    f"home"))

    # Gate references: any Name/Attribute read of a gate name outside
    # its declaring assignment.
    referenced: set[str] = set()
    multi_gate: list[tuple[str, int, frozenset]] = []
    for src in sources:
        for node in ast.walk(src.tree):
            test = None
            if isinstance(node, (ast.If, ast.IfExp)):
                test = node.test
            elif isinstance(node, ast.While):
                test = node.test
            if test is None:
                continue
            gates_here = set()
            for sub in ast.walk(test):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr in declared:
                    gates_here.add(sub.attr)
                elif isinstance(sub, ast.Name) and sub.id in declared:
                    gates_here.add(sub.id)
                # chaos.ACTIVE is the chaos gate.
                if isinstance(sub, ast.Attribute) \
                        and sub.attr == "ACTIVE":
                    gates_here.add("ACTIVE")
            referenced |= gates_here
            if len(gates_here - {"ACTIVE"}) > 1:
                multi_gate.append((src.rel, node.lineno,
                                   frozenset(gates_here)))

    for gate, where in sorted(declared.items()):
        if gate not in referenced and len(where) == 1:
            path, line = where[0]
            findings.append(Finding(
                "disarm-gates", path, line, f"unused.{gate}",
                f"disarm gate {gate!r} is never branched on — a plane "
                f"nothing can disarm (or a stale gate)"))

    for path, line, gates in multi_gate:
        ident = "multi." + "-".join(sorted(g for g in gates))
        findings.append(Finding(
            "disarm-gates", path, line, ident,
            f"one branch tests {len(gates)} disarm gates "
            f"({', '.join(sorted(gates))}) — a gated site belongs to "
            f"exactly one plane"))

    # Config-knob reads outside the home module / arming functions.
    for src in sources:
        if src.rel.startswith("ray_tpu/_private/analysis/"):
            continue
        spans = None
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Attribute)
                    and node.attr in KNOB_GATES):
                continue
            value = node.value
            is_config = (isinstance(value, ast.Name)
                         and "CONFIG" in value.id.upper()) \
                or (isinstance(value, ast.Attribute)
                    and "CONFIG" in value.attr.upper())
            if not is_config:
                continue
            home, gate = KNOB_GATES[node.attr]
            if src.rel == home or src.rel.endswith("/config.py"):
                continue
            if spans is None:
                spans = _enclosing_funcs(src.tree)
            if _in_arming_function(spans, node.lineno):
                continue
            findings.append(Finding(
                "disarm-gates", src.rel, node.lineno,
                f"knob.{node.attr}",
                f"config knob {node.attr!r} read outside its plane's "
                f"home module and outside an init/arming function — "
                f"gate the site on {gate} instead (one attribute "
                f"load, no config lock)"))
    return findings
