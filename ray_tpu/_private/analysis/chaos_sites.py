"""Pass ``chaos-sites``: the injection-site registry cannot drift.

``chaos.SITES`` (chaos.py) is the canonical list. This pass parses it
straight out of the AST (never importing the module) and enforces, in
both directions:

- every ``*.should("<site>")`` / ``maybe_partition`` site string used
  anywhere in the tree is registered in ``SITES``;
- every registered site is documented in chaos.py's module docstring
  (the operator-facing spec grammar);
- every registered site appears somewhere under ``tests/`` — an
  injection point nothing exercises is dead chaos;
- every registered site is actually drawn somewhere in the tree (a
  site with no ``should()`` caller is a stale registry row).
"""

from __future__ import annotations

import ast
import os

from ray_tpu._private.analysis import Finding, repo_root

CHAOS_REL = "ray_tpu/_private/chaos.py"


def _registry(sources) -> "tuple[set[str], str, int]":
    """(SITES entries, module docstring, SITES lineno) from chaos.py's
    AST."""
    for src in sources:
        if src.rel != CHAOS_REL:
            continue
        doc = ast.get_docstring(src.tree) or ""
        for node in src.tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if not any(isinstance(t, ast.Name) and t.id == "SITES"
                           for t in targets):
                    continue
                value = node.value
                if isinstance(value, ast.Tuple):
                    sites = {elt.value for elt in value.elts
                             if isinstance(elt, ast.Constant)
                             and isinstance(elt.value, str)}
                    return sites, doc, node.lineno
        return set(), doc, 1
    return set(), "", 1


def used_sites(sources) -> "dict[str, tuple[str, int]]":
    """{site -> first (path, line)} for every should("<lit>") call in
    the tree (chaos.py's own internal draw included)."""
    out: dict[str, tuple[str, int]] = {}
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) \
                else func.id if isinstance(func, ast.Name) else None
            if name != "should" or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                out.setdefault(arg.value, (src.rel, node.lineno))
    return out


def registered_sites(sources=None) -> "set[str]":
    """The SITES registry, parsed from chaos.py's AST (exported for
    tests/test_doc_drift.py so docs assertions share this parser)."""
    if sources is None:
        from ray_tpu._private.analysis import (
            default_package_root,
            iter_sources,
        )

        sources = iter_sources(default_package_root())
    sites, _, _ = _registry(sources)
    return sites


def _tests_text() -> str:
    tests_dir = os.path.join(repo_root(), "tests")
    chunks = []
    try:
        names = sorted(os.listdir(tests_dir))
    except OSError:
        return ""
    for name in names:
        if name.endswith((".py", ".cpp")):
            try:
                chunks.append(open(os.path.join(tests_dir, name),
                                   encoding="utf-8").read())
            except OSError:
                continue  # unreadable test file: skip it
    return "\n".join(chunks)


def run(sources) -> "list[Finding]":
    findings: list[Finding] = []
    sites, doc, sites_line = _registry(sources)
    if not sites:
        findings.append(Finding(
            "chaos-sites", CHAOS_REL, sites_line, "SITES",
            "chaos.py lost its SITES registry tuple"))
        return findings
    used = used_sites(sources)
    for site, (path, line) in sorted(used.items()):
        if site not in sites:
            findings.append(Finding(
                "chaos-sites", path, line, f"site.{site}",
                f"chaos site {site!r} drawn here but not registered "
                f"in chaos.SITES"))
    tests_text = _tests_text()
    for site in sorted(sites):
        if site not in doc:
            findings.append(Finding(
                "chaos-sites", CHAOS_REL, sites_line, f"doc.{site}",
                f"registered chaos site {site!r} missing from "
                f"chaos.py's docstring (the spec-grammar contract)"))
        if tests_text and site not in tests_text:
            findings.append(Finding(
                "chaos-sites", CHAOS_REL, sites_line, f"tests.{site}",
                f"registered chaos site {site!r} never appears under "
                f"tests/ — dead injection point"))
        if site not in used:
            findings.append(Finding(
                "chaos-sites", CHAOS_REL, sites_line, f"unused.{site}",
                f"registered chaos site {site!r} has no should() "
                f"caller in the tree — stale registry row"))
    return findings
