"""Serialization boundary for the multiprocess runtime.

TPU-native analogue of the reference's serialization layer
(python/ray/_private/serialization.py + the cloudpickle fork in
python/ray/cloudpickle/): cloudpickle for code/closures, pickle
protocol 5 out-of-band buffers for zero-copy numpy, and a framed
single-buffer layout so a whole object drops into one shared-memory
segment that workers map directly.

Layout of a framed object (all lengths little-endian uint64):

    [header_len][header bytes][n_buffers]
    [buf_0 len][buf_0 bytes] ... [buf_{n-1} len][buf_{n-1} bytes]

``deserialize_from_buffer`` reconstructs buffers as memoryviews into the
source buffer — numpy arrays come back zero-copy, viewing shared memory
directly (the moral equivalent of plasma's mmap reads,
src/ray/object_manager/plasma/client.h).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any

import cloudpickle

_U64 = struct.Struct("<Q")


def serialize(value: Any) -> tuple[bytes, list[pickle.PickleBuffer]]:
    """Serialize with out-of-band buffers (zero-copy for numpy)."""
    buffers: list[pickle.PickleBuffer] = []
    header = cloudpickle.dumps(value, protocol=5,
                               buffer_callback=buffers.append)
    return header, buffers


def deserialize(header: bytes, buffers: list) -> Any:
    return pickle.loads(header, buffers=buffers)


def framed_size(header: bytes, buffers: list[pickle.PickleBuffer]) -> int:
    total = _U64.size * 2 + len(header)
    for buf in buffers:
        total += _U64.size + memoryview(buf).nbytes
    return total


def write_framed(target: memoryview, header: bytes,
                 buffers: list[pickle.PickleBuffer]) -> int:
    """Write the framed layout into ``target``; returns bytes written."""
    off = 0

    def put(b) -> None:
        nonlocal off
        m = memoryview(b)
        if m.ndim != 1 or m.format != "B":
            m = m.cast("B")
        target[off:off + m.nbytes] = m
        off += m.nbytes

    put(_U64.pack(len(header)))
    put(header)
    put(_U64.pack(len(buffers)))
    for buf in buffers:
        m = memoryview(buf)
        put(_U64.pack(m.nbytes))
        put(m)
    return off


def serialize_framed(value: Any) -> bytes:
    header, buffers = serialize(value)
    out = bytearray(framed_size(header, buffers))
    write_framed(memoryview(out), header, buffers)
    return bytes(out)


def deserialize_from_buffer(source: memoryview) -> Any:
    """Read the framed layout; buffers are zero-copy views of ``source``."""
    off = 0

    def take(n: int) -> memoryview:
        nonlocal off
        view = source[off:off + n]
        off += n
        return view

    (header_len,) = _U64.unpack(bytes(take(_U64.size)))
    header = bytes(take(header_len))
    (n_buffers,) = _U64.unpack(bytes(take(_U64.size)))
    buffers = []
    for _ in range(n_buffers):
        (buf_len,) = _U64.unpack(bytes(take(_U64.size)))
        buffers.append(take(buf_len))
    return pickle.loads(header, buffers=buffers)


def dumps_function(func: Any) -> bytes:
    """Pickle code (functions, classes, closures) by value when needed —
    the function-manager boundary (reference:
    python/ray/_private/function_manager.py exports to GCS KV)."""
    return cloudpickle.dumps(func, protocol=5)


def loads_function(blob: bytes) -> Any:
    return pickle.loads(blob)
