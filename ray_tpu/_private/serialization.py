"""Serialization boundary for the multiprocess runtime.

TPU-native analogue of the reference's serialization layer
(python/ray/_private/serialization.py + the cloudpickle fork in
python/ray/cloudpickle/): cloudpickle for code/closures, pickle
protocol 5 out-of-band buffers for zero-copy numpy, and a framed
single-buffer layout so a whole object drops into one shared-memory
segment that workers map directly.

Layout of a framed object (all lengths little-endian uint64):

    [header_len][header bytes][n_buffers]
    [buf_0 len][buf_0 bytes] ... [buf_{n-1} len][buf_{n-1} bytes]

``deserialize_from_buffer`` reconstructs buffers as memoryviews into the
source buffer — numpy arrays come back zero-copy, viewing shared memory
directly (the moral equivalent of plasma's mmap reads,
src/ray/object_manager/plasma/client.h).
"""

from __future__ import annotations

import pickle
import struct
import threading
from typing import Any

import cloudpickle

_U64 = struct.Struct("<Q")

# Raw small-immutable framing (the worker-pipe fast path): eligible
# values are encoded with a compact tag-length scheme instead of a
# cloudpickle round trip. A raw frame starts with a header length no
# pickled frame can produce (2**64 - 1), so readers distinguish the two
# layouts from the first 8 bytes — decoding support is unconditional,
# only PRODUCING raw frames is gated (RAW_ON, armed from the
# raw_framing knob; disarmed frames are byte-identical pickles).
RAW_ON: bool = True
_RAW_SENTINEL = (1 << 64) - 1
_RAW_SENTINEL_BYTES = _U64.pack(_RAW_SENTINEL)
# Values above this never take the raw path: the win is the per-tiny-
# object pickle overhead, not bulk encode throughput.
_RAW_MAX_BYTES = 8192
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


def init_raw_from_config() -> None:
    """Arm/disarm the raw framing fast path from config (Runtime init
    and daemon/worker boot paths call this; import falls back to the
    env-overridden default)."""
    global RAW_ON
    from ray_tpu._private.config import GLOBAL_CONFIG

    RAW_ON = bool(GLOBAL_CONFIG.raw_framing)


try:
    init_raw_from_config()
except Exception:  # noqa: BLE001 — config unavailable mid-bootstrap
    pass


class _RawIneligible(Exception):
    """Internal: the value contains a type the raw encoding has no tag
    for (or is too large) — caller falls back to the pickle path."""


_scratch = threading.local()


def _raw_encode(out: bytearray, value: Any) -> None:
    # Exact type checks only: subclasses (np.float64, IntEnum, ...)
    # must round-trip through pickle to preserve their type.
    t = type(value)
    if value is None:
        out.append(0x4E)  # 'N'
    elif t is bool:
        out.append(0x54 if value else 0x46)  # 'T' / 'F'
    elif t is int:
        if not _I64_MIN <= value <= _I64_MAX:
            raise _RawIneligible
        out.append(0x69)  # 'i'
        out += _I64.pack(value)
    elif t is float:
        out.append(0x66)  # 'f'
        out += _F64.pack(value)
    elif t is str:
        b = value.encode("utf-8")
        out.append(0x73)  # 's'
        out += _U32.pack(len(b))
        out += b
    elif t is bytes:
        out.append(0x62)  # 'b'
        out += _U32.pack(len(value))
        out += value
    elif t is tuple:
        out.append(0x74)  # 't'
        out += _U32.pack(len(value))
        for item in value:
            _raw_encode(out, item)
    elif t is dict:
        out.append(0x64)  # 'd'
        out += _U32.pack(len(value))
        for k, v in value.items():
            if type(k) is not str:
                raise _RawIneligible
            kb = k.encode("utf-8")
            out += _U32.pack(len(kb))
            out += kb
            _raw_encode(out, v)
    else:
        raise _RawIneligible
    if len(out) > _RAW_MAX_BYTES:
        raise _RawIneligible


def try_serialize_raw(value: Any) -> "bytes | None":
    """Frame ``value`` with the raw small-immutable encoding, or None
    when it is ineligible (unsupported type, too large) or the fast
    path is disarmed. The returned blob is a drop-in replacement for a
    ``serialize_framed`` blob — ``deserialize_from_buffer`` dispatches
    on the sentinel prefix."""
    if not RAW_ON:
        return None
    out = getattr(_scratch, "buf", None)
    if out is None:
        out = _scratch.buf = bytearray()
    else:
        del out[:]
    out += _RAW_SENTINEL_BYTES
    try:
        _raw_encode(out, value)
    except _RawIneligible:
        return None
    return bytes(out)


def _raw_decode(source: memoryview, off: int) -> tuple[Any, int]:
    tag = source[off]
    off += 1
    if tag == 0x4E:
        return None, off
    if tag == 0x54:
        return True, off
    if tag == 0x46:
        return False, off
    if tag == 0x69:
        return _I64.unpack(source[off:off + 8])[0], off + 8
    if tag == 0x66:
        return _F64.unpack(source[off:off + 8])[0], off + 8
    if tag == 0x73:
        (n,) = _U32.unpack(source[off:off + 4])
        off += 4
        return str(source[off:off + n], "utf-8"), off + n
    if tag == 0x62:
        (n,) = _U32.unpack(source[off:off + 4])
        off += 4
        return bytes(source[off:off + n]), off + n
    if tag == 0x74:
        (n,) = _U32.unpack(source[off:off + 4])
        off += 4
        items = []
        for _ in range(n):
            item, off = _raw_decode(source, off)
            items.append(item)
        return tuple(items), off
    if tag == 0x64:
        (n,) = _U32.unpack(source[off:off + 4])
        off += 4
        d = {}
        for _ in range(n):
            (kn,) = _U32.unpack(source[off:off + 4])
            off += 4
            key = str(source[off:off + kn], "utf-8")
            off += kn
            d[key], off = _raw_decode(source, off)
        return d, off
    raise ValueError(f"corrupt raw frame: unknown tag {tag:#x}")


def serialize(value: Any) -> tuple[bytes, list[pickle.PickleBuffer]]:
    """Serialize with out-of-band buffers (zero-copy for numpy)."""
    buffers: list[pickle.PickleBuffer] = []
    header = cloudpickle.dumps(value, protocol=5,
                               buffer_callback=buffers.append)
    return header, buffers


def deserialize(header: bytes, buffers: list) -> Any:
    return pickle.loads(header, buffers=buffers)


def framed_size(header: bytes, buffers: list[pickle.PickleBuffer]) -> int:
    total = _U64.size * 2 + len(header)
    for buf in buffers:
        total += _U64.size + memoryview(buf).nbytes
    return total


def write_framed(target: memoryview, header: bytes,
                 buffers: list[pickle.PickleBuffer]) -> int:
    """Write the framed layout into ``target``; returns bytes written."""
    off = 0

    def put(b) -> None:
        nonlocal off
        m = memoryview(b)
        if m.ndim != 1 or m.format != "B":
            m = m.cast("B")
        target[off:off + m.nbytes] = m
        off += m.nbytes

    put(_U64.pack(len(header)))
    put(header)
    put(_U64.pack(len(buffers)))
    for buf in buffers:
        m = memoryview(buf)
        put(_U64.pack(m.nbytes))
        put(m)
    return off


def serialize_framed(value: Any) -> bytes:
    header, buffers = serialize(value)
    out = bytearray(framed_size(header, buffers))
    write_framed(memoryview(out), header, buffers)
    return bytes(out)


def deserialize_from_buffer(source: memoryview) -> Any:
    """Read the framed layout; buffers are zero-copy views of ``source``.

    A raw small-immutable frame (sentinel header length) decodes via
    the tag scheme instead — one u64 compare on every classic frame."""
    if len(source) >= 8 and bytes(source[:8]) == _RAW_SENTINEL_BYTES:
        value, _ = _raw_decode(source, 8)
        return value
    off = 0

    def take(n: int) -> memoryview:
        nonlocal off
        view = source[off:off + n]
        off += n
        return view

    (header_len,) = _U64.unpack(bytes(take(_U64.size)))
    header = bytes(take(header_len))
    (n_buffers,) = _U64.unpack(bytes(take(_U64.size)))
    buffers = []
    for _ in range(n_buffers):
        (buf_len,) = _U64.unpack(bytes(take(_U64.size)))
        buffers.append(take(buf_len))
    return pickle.loads(header, buffers=buffers)


def dumps_function(func: Any) -> bytes:
    """Pickle code (functions, classes, closures) by value when needed —
    the function-manager boundary (reference:
    python/ray/_private/function_manager.py exports to GCS KV)."""
    return cloudpickle.dumps(func, protocol=5)


def loads_function(blob: bytes) -> Any:
    return pickle.loads(blob)
