"""Node executor service — the cluster's distributed execution plane.

TPU-native analogue of the raylet's lease-and-dispatch loop plus the
object manager's node-to-node transfer:

- ``NodeExecutorService`` runs inside every worker-node daemon and
  serves ``execute_task`` over RPC (reference: the raylet grants a
  worker lease and the task is pushed to that node's worker pool —
  src/ray/raylet/node_manager.cc:1714 HandleRequestWorkerLease,
  local_task_manager.h:58). CPU tasks run on the node's own
  multiprocess worker pool; TPU tasks run in the daemon process (which
  owns the node's JAX/TPU runtime).
- ``NodeObjectStore`` holds serialized task results and pulled objects;
  peers and the driver read them with chunked ``fetch_object`` RPCs
  (reference: src/ray/object_manager/object_manager.h:106-130 —
  chunked Push/Pull between nodes).
- ``RemoteNodeHandle`` is the driver side: it leases the task to the
  node, ships the function once per node by digest (function-manager
  pattern), passes remote-located args as ``FetchRef`` location hints
  so the consuming node pulls them peer-to-peer — the driver never
  relays the bytes (reference: ownership_based_object_directory.h, the
  owner hands out locations, data flows node-to-node).

Results above the inline threshold stay on the producing node; the
driver's store holds a ``RemoteBlob`` placeholder that materializes by
chunked pull only when the value is actually read locally.
"""

from __future__ import annotations

import collections
import os
import threading

from ray_tpu._private import lock_witness
import time
from dataclasses import dataclass
from typing import Any, Callable

from ray_tpu._private import perf_plane as perf
from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.rpc import (
    MuxRpcClient,
    RpcClient,
    RpcError,
    RpcMethodError,
    RpcServer,
)

# Results at or below executor_inline_reply_kb (config) ship inline in
# the execute_task reply; larger ones stay in the producing node's
# store (driver pulls lazily in fetch_chunk_kb chunks).


def _inline_reply_bytes() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return int(GLOBAL_CONFIG.executor_inline_reply_kb) * 1024


def _fetch_chunk_bytes() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return int(GLOBAL_CONFIG.fetch_chunk_kb) * 1024


@dataclass
class FetchRef:
    """Arg placeholder: the value lives in a node's object store —
    resolve by local lookup or a chunked pull from ``addr``."""

    id_bytes: bytes
    addr: str


@dataclass
class RemoteBlob:
    """Driver-store placeholder for a result held on a remote node."""

    node_hex: str
    addr: str
    size: int


class NodeBusyError(Exception):
    """The node rejected the lease at admission (another driver's work
    saturates it); the submitter should spill to a different node."""


class TaskSpeculationCancelled(Exception):
    """The daemon refused the execution because its task token was
    cancelled (speculation first-seal-wins: a sibling copy already
    sealed the result) — nothing ran, nothing to seal."""


class NodeOverloadedError(Exception):
    """The node SHED the lease at admission (queue-depth cap, memory
    watermark, or the overload.saturate chaos site): distinct from
    plain busy — the driver fails deadline-armed tasks fast with
    SystemOverloadedError instead of spilling them into a backlog."""


class TaskDeadlineExpired(Exception):
    """Internal driver-side signal: the daemon found the task's
    end-to-end deadline already dead and refused to execute it."""


# Fused in-daemon execution (the fused_execution knob): runs of tiny
# DEFAULT tasks inside an execute_task_batch RPC execute directly on
# the daemon's dispatch thread — no worker-pipe hop — bounded by the
# fused_max_run_tasks / fused_run_wall_budget_s per-run budget.
# Disarmed cost is this one module-attribute branch per site (the
# chaos.ACTIVE / perf.PERF_ON discipline); daemons inherit
# RAY_TPU_FUSED_EXECUTION through the child env at import.
FUSED_ON: bool = True


def init_fused_from_config() -> None:
    """Arm/disarm fused in-daemon execution from config (Runtime init
    and daemon boot both reach this through import)."""
    global FUSED_ON
    from ray_tpu._private.config import GLOBAL_CONFIG

    FUSED_ON = bool(GLOBAL_CONFIG.fused_execution)


try:
    init_fused_from_config()
except Exception:  # noqa: BLE001 — config unavailable mid-bootstrap
    pass

# Canonical executor_stats() counter keys, exported so the README
# doc-drift check (tests/test_doc_drift.py) can assert every counter is
# documented without standing up a daemon.
PIPELINE_STAT_KEYS = ("batch_rpcs", "batch_tasks", "reply_groups",
                      "worker_lease_runs", "worker_lease_tasks",
                      "worker_pipelined_frames",
                      "fused_runs", "fused_tasks", "fused_fallbacks",
                      "runner_spawns", "runner_reuses")
DATA_PLANE_STAT_KEYS = ("same_host_map_hits", "same_host_copy_hits",
                        "chunked_pulls", "map_sources",
                        "attached_mappings", "leases")
FAULT_STAT_KEYS = ("rpc_retries", "batch_requeues", "peer_blacklists",
                   "lease_orphans_swept", "arena_orphans_swept",
                   "lineage_rebuilds", "task_timeouts",
                   "admission_shed", "breaker_open")
# Always-on performance-plane stage names (perf_plane.py): every hop a
# process can measure inside its own clock. Daemon stages ship on
# heartbeats; driver stages export straight from the local registry.
STAGE_HIST_KEYS = ("submit_dispatch", "dispatch_rpc", "rpc_seal",
                   "exec_local", "admit_worker", "exec")


def _proc_label() -> str:
    """This daemon's process-lane label in merged timelines."""
    tag = os.environ.get("RAY_TPU_NODE_TAG", "")
    return f"node:{tag[:8]}" if tag else f"node:pid{os.getpid()}"


class NodeObjectStore:
    """Serialized-blob store of a node daemon: task/actor results
    (primary copies, owner-tagged, spillable to disk past the cap) +
    pulled peer objects (evictable cache).

    Reference: the raylet's LocalObjectManager — primary copies live
    until the owner frees them or dies (local_object_manager.h:110
    SpillObjects / owner-death cleanup)."""

    def __init__(self, cache_limit_bytes: int | None = None,
                 primary_limit_bytes: int | None = None,
                 spill_dir: str | None = None):
        from ray_tpu._private.config import GLOBAL_CONFIG

        self._lock = lock_witness.Lock("node_executor.NodeObjectStore")
        self._blobs: dict[bytes, bytes] = {}  # insertion-ordered
        self._cached: dict[bytes, None] = {}  # pulled copies, FIFO evict
        self._cache_limit = (
            cache_limit_bytes if cache_limit_bytes is not None
            else int(GLOBAL_CONFIG.node_pull_cache_mb) * 1024 * 1024)
        self._cache_bytes = 0
        self._primary_limit = (
            primary_limit_bytes if primary_limit_bytes is not None
            else int(GLOBAL_CONFIG.node_store_primary_limit_mb) * 1024 * 1024)
        self._spill_dir = (spill_dir or GLOBAL_CONFIG.node_store_spill_dir)
        self._primary_bytes = 0
        # id -> (path, size): primaries moved to disk; restored on fetch.
        self._spilled: dict[bytes, tuple[str, int]] = {}
        # Managed spill tier (spill_manager.py, armed via
        # enable_managed_spill): watermark-driven async spilling with
        # checksummed session-dir files replaces the legacy inline
        # cap-based path. _managed_spills marks which _spilled entries
        # use the headered format.
        self._spill_mgr = None
        self._managed_spills: set[bytes] = set()
        self._spill_min_bytes = 0
        self._leased_fn = None
        self._on_spilled = None
        self._on_restored = None
        # Ownership: id -> owner key; owner -> ids (owner-death sweep).
        self._owner_of: dict[bytes, str] = {}
        self._owned_ids: dict[str, set[bytes]] = {}
        self.fetches_served = 0
        self.spills = 0
        self.restores = 0
        self._purge_stale_spills()

    def _purge_stale_spills(self) -> None:
        """Delete spill files left by crashed prior daemons (shared
        helper — pid-prefixed filenames, liveness-checked)."""
        from ray_tpu._private.node_store_native import purge_stale_spills

        purge_stale_spills(self._spill_dir)

    def enable_managed_spill(self, spill_dir: str | None = None,
                             leased_fn=None, on_spilled=None,
                             on_restored=None):
        """Arm the watermark-driven spill tier on this store: primaries
        above spill_high_watermark x the primary cap move to
        checksummed files asynchronously (legacy inline spilling is
        bypassed), freeing memory AND — via ``on_spilled`` — any
        shm/arena twin. ``leased_fn`` returns the id set currently
        pinned by same-host peers (never spilled); ``on_restored``
        fires after a transparent restore re-registers the copy in
        memory. Returns the SpillManager."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.spill_manager import SpillManager

        self._leased_fn = leased_fn
        self._on_spilled = on_spilled
        self._on_restored = on_restored
        self._spill_min_bytes = \
            int(GLOBAL_CONFIG.spill_min_object_kb) * 1024
        self._spill_mgr = SpillManager(
            "node-store", self._primary_limit,
            usage_fn=lambda: self._primary_bytes,
            victims_fn=self._spill_victims,
            extract_fn=self._spill_extract,
            commit_fn=self._spill_commit,
            spill_dir=spill_dir)
        return self._spill_mgr

    def _spill_victims(self, need_bytes: int) -> list:
        """Spillable keys covering ``need_bytes``: PRIMARY copies only
        (pulled cache copies already evict), never ids leased to
        same-host peers, size floor applied — ordered size-descending
        (fewest files free the most bytes) with insertion (FIFO/LRU)
        age as the tiebreak."""
        leased: set = set()
        if self._leased_fn is not None:
            try:
                leased = set(self._leased_fn())
            except Exception:  # noqa: BLE001 — no filter beats no spill
                leased = set()
        with self._lock:
            cands = [(key, len(blob), age)
                     for age, (key, blob) in enumerate(self._blobs.items())
                     if key not in self._cached and key not in leased
                     and len(blob) >= self._spill_min_bytes]
        cands.sort(key=lambda c: (-c[1], c[2]))
        out, covered = [], 0
        for key, size, _age in cands:
            out.append(key)
            covered += size
            if covered >= need_bytes:
                break
        return out

    def _spill_extract(self, key: bytes):
        with self._lock:
            if key in self._cached:
                return None
            return self._blobs.get(key)

    def _spill_commit(self, key: bytes, path: str, size: int) -> bool:
        with self._lock:
            blob = self._blobs.get(key)
            if blob is None or key in self._cached or len(blob) != size:
                return False  # freed/resealed since extraction
            del self._blobs[key]
            self._primary_bytes -= size
            self._spilled[key] = (path, size)
            self._managed_spills.add(key)
            self.spills += 1
            owner = self._owner_of.get(key)
        if self._on_spilled is not None:
            self._on_spilled(key, owner)
        return True

    def _restore_managed(self, key: bytes) -> bytes | None:
        """Transparent restore of a managed spilled primary: verify the
        checksummed file, re-insert the blob as the in-memory primary
        (the node is a full holder again — ``on_restored`` clears the
        directory's spill mark), delete the file. Concurrent restores
        race benignly on the path snapshot; a torn file drops the
        entry entirely (the caller sees absence and the owner falls
        back to lineage reconstruction)."""
        from ray_tpu._private.spill_manager import TornSpillError

        mgr = self._spill_mgr
        while True:
            with self._lock:
                blob = self._blobs.get(key)
                if blob is not None:
                    return blob
                entry = self._spilled.get(key)
                if entry is None:
                    return None  # freed (or torn-dropped) meanwhile
                path, size = entry
            try:
                payload = bytes(mgr.restore(key, path))
            except TornSpillError:
                with self._lock:
                    if self._spilled.get(key) == (path, size):
                        # The disk copy is garbage and the memory copy
                        # is long gone: the object is LOST here. Drop
                        # it entirely so fetchers see absence and the
                        # owner reconstructs from lineage.
                        self._forget_locked(key)
                return None
            except OSError:
                continue  # another reader restored + unlinked; re-check
            with self._lock:
                if self._spilled.get(key) != (path, size):
                    if key in self._blobs:
                        # Another reader restored it first: our
                        # verified payload is the same bytes.
                        return self._blobs[key]
                    continue  # raced a free; re-check
                del self._spilled[key]
                self._managed_spills.discard(key)
                self._blobs[key] = payload
                self._primary_bytes += size
                self.restores += 1
                owner = self._owner_of.get(key)
            try:
                os.unlink(path)
            except OSError:
                pass  # restored copy is safe; file is tidy-up
            if self._on_restored is not None:
                self._on_restored(key, owner)
            # The restore may have pushed usage back over the HIGH
            # watermark: let the spiller pick a different victim.
            mgr.notify()
            return payload

    def put(self, id_bytes: bytes, blob: bytes, cached: bool = False,
            owner: str | None = None) -> None:
        spill_victims: list[tuple[bytes, bytes]] = []
        with self._lock:
            old = self._blobs.get(id_bytes)
            if old is not None and id_bytes in self._cached:
                self._cache_bytes -= len(old)
                del self._cached[id_bytes]
            elif old is not None:
                self._primary_bytes -= len(old)
            self._drop_spilled(id_bytes)
            self._blobs[id_bytes] = blob
            if owner is not None and not cached:
                self._owner_of[id_bytes] = owner
                self._owned_ids.setdefault(owner, set()).add(id_bytes)
            if cached:
                self._cached[id_bytes] = None
                self._cache_bytes += len(blob)
                while self._cache_bytes > self._cache_limit and self._cached:
                    victim = next(iter(self._cached))
                    del self._cached[victim]
                    dropped = self._blobs.pop(victim, None)
                    if dropped is not None:
                        self._cache_bytes -= len(dropped)
            else:
                self._primary_bytes += len(blob)
                if self._spill_mgr is None:
                    # Legacy inline path (spill_enabled=0): over the
                    # cap, spill the OLDEST primaries to disk (the
                    # newest blob is the one most likely to be fetched
                    # next). Victims are only SELECTED here — they stay
                    # readable in _blobs until the disk write lands
                    # (_spill_one), so a concurrent fetch/free never
                    # sees the object in neither map.
                    projected = self._primary_bytes
                    for victim in list(self._blobs):
                        if projected <= self._primary_limit:
                            break
                        if victim in self._cached or victim == id_bytes:
                            continue
                        vblob = self._blobs[victim]
                        projected -= len(vblob)
                        spill_victims.append((victim, vblob))
        for victim, vblob in spill_victims:
            self._spill_one(victim, vblob)
        if self._spill_mgr is not None and not cached:
            # Managed tier: one usage-vs-watermark comparison; the
            # async spiller does the victim work off the put path.
            self._spill_mgr.notify()

    def _spill_one(self, id_bytes: bytes, blob: bytes) -> None:
        os.makedirs(self._spill_dir, exist_ok=True)
        # Unique per attempt: two concurrent put()s may both pick this
        # victim; each must own its file so the loser's cleanup cannot
        # unlink the winner's registered copy.
        path = os.path.join(
            self._spill_dir,
            f"{os.getpid()}-{id_bytes.hex()}-{os.urandom(4).hex()}.blob")
        try:
            with open(path, "wb") as f:
                f.write(blob)
        except OSError:
            return  # disk full/unwritable: blob simply stays in memory
        with self._lock:
            # The blob stayed visible during the write; only now swap it
            # to the disk copy — unless a concurrent free() removed it
            # or a reseal replaced it, in which case the file is stale.
            if self._blobs.get(id_bytes) is not blob:
                stale = True
            else:
                del self._blobs[id_bytes]
                self._primary_bytes -= len(blob)
                self._spilled[id_bytes] = (path, len(blob))
                self.spills += 1
                stale = False
        if stale:
            try:
                os.unlink(path)
            except OSError:
                pass  # stale spill file already swept

    def _drop_spilled(self, id_bytes: bytes) -> None:
        # Caller holds self._lock.
        entry = self._spilled.pop(id_bytes, None)
        managed = id_bytes in self._managed_spills
        self._managed_spills.discard(id_bytes)
        if entry is not None:
            if managed and self._spill_mgr is not None:
                # free/owner-death pruning of a managed spill file —
                # counted (files_deleted) + flight-recorded.
                self._spill_mgr.delete_file(entry[0])
                return
            try:
                os.unlink(entry[0])
            except OSError:
                pass  # spill file already gone

    def get(self, id_bytes: bytes) -> bytes | None:
        with self._lock:
            blob = self._blobs.get(id_bytes)
            spilled = self._spilled.get(id_bytes)
            managed = id_bytes in self._managed_spills
        if blob is not None:
            return blob
        if spilled is not None:
            if managed:
                # Checksum-verified restore that re-registers the blob
                # as the in-memory primary (None on a torn file — the
                # object is lost here, lineage rebuilds it).
                return self._restore_managed(id_bytes)
            try:
                with open(spilled[0], "rb") as f:
                    data = f.read()
            except OSError:
                return None
            with self._lock:
                self.restores += 1
            return data
        return None

    def _forget_locked(self, id_bytes: bytes) -> bool:
        # _locked suffix: caller holds self._lock (the lock-discipline
        # pass verifies the convention). Returns True if the id existed.
        existed = False
        blob = self._blobs.pop(id_bytes, None)
        if blob is not None:
            existed = True
            if id_bytes in self._cached:
                del self._cached[id_bytes]
                self._cache_bytes -= len(blob)
            else:
                self._primary_bytes -= len(blob)
        if id_bytes in self._spilled:
            existed = True
            self._drop_spilled(id_bytes)
        owner = self._owner_of.pop(id_bytes, None)
        if owner is not None:
            ids = self._owned_ids.get(owner)
            if ids is not None:
                ids.discard(id_bytes)
                if not ids:
                    del self._owned_ids[owner]
        return existed

    def free(self, ids: list[bytes]) -> int:
        with self._lock:
            return sum(1 for id_bytes in ids if self._forget_locked(id_bytes))

    def free_owner(self, owner: str) -> int:
        """Owner-death sweep: drop every primary the owner left here."""
        with self._lock:
            ids = list(self._owned_ids.get(owner, ()))
            return sum(1 for id_bytes in ids if self._forget_locked(id_bytes))

    def owners(self) -> list[str]:
        with self._lock:
            return list(self._owned_ids)

    def size(self, id_bytes: bytes) -> int | None:
        """Byte size of a stored blob without copying it (and without
        counting as a served fetch — used by transfer-plan probes)."""
        with self._lock:
            blob = self._blobs.get(id_bytes)
            if blob is not None:
                return len(blob)
            spilled = self._spilled.get(id_bytes)
            if spilled is not None:
                return spilled[1]
        return None

    def is_spilled(self, id_bytes: bytes) -> bool:
        """True while the only local copy lives on disk (fetch plans
        advertise it so pullers know a restore precedes the bytes)."""
        with self._lock:
            return (id_bytes in self._spilled
                    and id_bytes not in self._blobs)

    def read_chunk(self, id_bytes: bytes, offset: int,
                   length: int) -> tuple[int, bytes] | None:
        with self._lock:
            blob = self._blobs.get(id_bytes)
            spilled = self._spilled.get(id_bytes)
            managed = id_bytes in self._managed_spills
            if blob is not None:
                self.fetches_served += 1
                return len(blob), blob[offset:offset + length]
        if spilled is not None and managed:
            # Managed tier: restore the WHOLE object once (checksum
            # verification needs the full payload; the restore
            # re-registers this node as an in-memory holder) and serve
            # every chunk from memory — torn files surface as absence,
            # never as silently corrupt chunks.
            blob = self._restore_managed(id_bytes)
            if blob is None:
                return None
            with self._lock:
                self.fetches_served += 1
            return len(blob), blob[offset:offset + length]
        if spilled is None:
            return None
        # Spilled primary: stream the chunk straight from disk (restore
        # on fetch — reference: spilled_object_reader.h).
        path, size = spilled
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                chunk = f.read(length)
        except OSError:
            return None
        with self._lock:
            self.fetches_served += 1
            self.restores += 1
        return size, chunk

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_blobs": len(self._blobs),
                "bytes": sum(len(b) for b in self._blobs.values()),
                "fetches_served": self.fetches_served,
                "spilled_blobs": len(self._spilled),
                "spilled_bytes": sum(s for _, s in self._spilled.values()),
                "spills": self.spills,
                "restores": self.restores,
                "owners": len(self._owned_ids),
            }


class _PeerClients:
    """One multiplexed RPC client per peer address (daemon-side pulls:
    concurrent chunk fetches interleave on a single socket per pair)."""

    def __init__(self):
        self._lock = lock_witness.Lock("node_executor._PeerClients")
        self._clients: dict[str, MuxRpcClient] = {}

    def get(self, addr: str) -> MuxRpcClient:
        with self._lock:
            client = self._clients.get(addr)
            if client is None:
                client = MuxRpcClient(addr, timeout_s=600.0)
                self._clients[addr] = client
            return client

    def close(self) -> None:
        with self._lock:
            for client in self._clients.values():
                client.close()
            self._clients.clear()


def _pipeline_depth() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return max(1, int(GLOBAL_CONFIG.rpc_pipeline_depth))


def fetch_blob(client: RpcClient, id_bytes: bytes) -> bytes:
    """Chunked pull of one object (reference: object_manager.h chunked
    Push — here pull-oriented, sized by fetch_chunk_kb). On a pipelined
    client (MuxRpcClient) up to rpc_pipeline_depth chunk requests ride
    the socket concurrently, so throughput is not bounded by one
    round-trip per chunk."""
    from collections import deque

    chunk_bytes = _fetch_chunk_bytes()
    first = client.call("fetch_object", id_bytes, 0, chunk_bytes)
    if first is None:
        raise KeyError(
            f"object {id_bytes.hex()} not present on {client.address}")
    total, chunk = first
    if len(chunk) >= total:
        return bytes(chunk)
    buf = bytearray(total)
    buf[:len(chunk)] = chunk
    offset = len(chunk)
    call_async = getattr(client, "call_async", None)
    if call_async is None:
        while offset < total:
            reply = client.call("fetch_object", id_bytes, offset,
                                chunk_bytes)
            if reply is None:
                raise KeyError(
                    f"object {id_bytes.hex()} vanished from "
                    f"{client.address}")
            _, chunk = reply
            buf[offset:offset + len(chunk)] = chunk
            offset += len(chunk)
        return bytes(buf)
    pending: deque = deque()
    depth = _pipeline_depth()
    next_off = offset
    while next_off < total or pending:
        while next_off < total and len(pending) < depth:
            pending.append((next_off, call_async(
                "fetch_object", id_bytes, next_off, chunk_bytes)))
            next_off += chunk_bytes
        off, slot = pending.popleft()
        reply = slot.result()
        if reply is None:
            raise KeyError(
                f"object {id_bytes.hex()} vanished from {client.address}")
        _, chunk = reply
        buf[off:off + len(chunk)] = chunk
    return bytes(buf)


class ChunkDirectory:
    """Owner-side holder registry for one node's (or the driver export
    server's) objects: every puller that starts fetching an object
    registers here and is handed the current holder set, so later
    pullers spread their chunk fetches across peers instead of queueing
    on the owner (reference: ownership_based_object_directory.h — the
    owner hands out locations, data flows node-to-node)."""

    TTL_S = 180.0

    def __init__(self):
        self._lock = lock_witness.Lock("node_executor.ChunkDirectory")
        # id -> {holder addr -> registered-at monotonic}
        self._holders: dict[bytes, dict[str, float]] = {}

    def register(self, id_bytes: bytes, addr: str | None) -> list[str]:
        """Record ``addr`` as a (partial) holder; return the OTHER
        currently-known holders, oldest first (oldest have the most
        chunks)."""
        import time

        now = time.monotonic()
        with self._lock:
            table = self._holders.setdefault(id_bytes, {})
            for holder, seen in list(table.items()):
                if now - seen > self.TTL_S:
                    del table[holder]
            others = [a for a in table if a != addr]
            if addr:
                table.setdefault(addr, now)
            return others

    def drop(self, ids: list[bytes]) -> None:
        with self._lock:
            for id_bytes in ids:
                self._holders.pop(id_bytes, None)

    def prune(self) -> None:
        import time

        now = time.monotonic()
        with self._lock:
            for id_bytes in list(self._holders):
                table = self._holders[id_bytes]
                for holder, seen in list(table.items()):
                    if now - seen > self.TTL_S:
                        del table[holder]
                if not table:
                    del self._holders[id_bytes]


def wrap_chunk_reply(reply):
    """Bulk chunk replies ship as raw tail bytes (TailPayload): the
    payload crosses the RPC layer without a pickle memcpy on either
    side. Small replies keep the plain tuple shape."""
    from ray_tpu._private.rpc import TailPayload

    total, chunk = reply
    if len(chunk) >= (1 << 16):
        return TailPayload(total, chunk)
    return (total, bytes(chunk) if isinstance(chunk, memoryview)
            else chunk)


def plan_holders(directory: ChunkDirectory, id_bytes: bytes,
                 puller_addr: str | None, total: int) -> list[str]:
    """Directory half of a fetch_plan reply: register the puller and
    return the other holders — but only for objects large enough that
    pullers actually take the P2P path; registering sub-threshold
    pullers would advertise peers that never hold servable chunks."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    chunk = _fetch_chunk_bytes()
    n_chunks = -(-total // chunk) if total else 0
    if n_chunks < int(GLOBAL_CONFIG.broadcast_min_p2p_chunks):
        return []
    return directory.register(id_bytes, puller_addr)


class _PartialBlob:
    """An in-progress (or recently finished) pull whose present chunks
    are servable to peers — the relay half of the broadcast tree: a
    receiver starts re-serving chunks the moment it has them, so 1->N
    broadcast throughput scales with the receivers, not the owner's
    socket (Podracer-style weight broadcast; reference: the object
    manager's chunked transfers + directory)."""

    __slots__ = ("total", "chunk", "buf", "have", "lock", "done",
                 "error", "completed_at", "served", "external")

    def __init__(self, total: int, chunk: int, buf=None):
        self.total = total
        self.chunk = chunk
        # ``buf`` may be an external writable buffer (a shared-memory
        # mapping): chunks then land directly where the consuming
        # worker will map them — zero intermediate full-object copies.
        self.external = buf is not None
        self.buf = buf if buf is not None else bytearray(total)
        self.have: set[int] = set()
        self.lock = lock_witness.Lock("node_executor._PartialBlob")
        self.done = threading.Event()
        self.error: BaseException | None = None
        self.completed_at: float | None = None
        self.served = 0  # chunks relayed to peers from this partial

    def n_chunks(self) -> int:
        return -(-self.total // self.chunk) if self.total else 0

    def write(self, index: int, data) -> None:
        off = index * self.chunk
        with self.lock:
            self.buf[off:off + len(data)] = data
            self.have.add(index)

    def read_chunk(self, offset: int, length: int):
        """Serve a range iff every covered chunk is present; None
        otherwise (the puller falls back to another holder)."""
        if offset >= self.total:
            return (self.total, b"")
        end = min(offset + length, self.total)
        first = offset // self.chunk
        last = (end - 1) // self.chunk if end > offset else first
        with self.lock:
            if any(i not in self.have for i in range(first, last + 1)):
                return None
            try:
                data = bytes(self.buf[offset:end])
            except ValueError:
                return None  # buffer released by concurrent eviction
            self.served += 1
            return (self.total, data)

    def finish(self) -> bytes | None:
        """Mark complete; returns the assembled bytes for internal
        buffers (external/shm buffers ARE the final resting place — no
        copy is made and None is returned)."""
        import time

        blob = None
        if not self.external:
            with self.lock:
                blob = bytes(self.buf)
        self.completed_at = time.monotonic()
        self.done.set()
        return blob

    def fail(self, exc: BaseException) -> None:
        self.error = exc
        self.done.set()


class _PipelineInflight:
    """Per-lease token ordering for the pipelined execute path, used
    for blocked-head parking: when the task at a lease's pipe head
    blocks in a nested get(), the frames queued behind it are sent but
    NOT running — their CPU reservations must be returned (daemon
    ledger via task_block, driver ledger via a streamed "parked"
    notification) or a nested child needing that capacity deadlocks
    against tasks that cannot start until the head resumes."""

    def __init__(self, service: "NodeExecutorService"):
        self._service = service
        self._lock = lock_witness.Lock("node_executor._PipelineInflight")
        self._leases: dict = {}        # lease key -> [token, ...]
        self._token_lease: dict = {}   # token -> lease key
        self._parked: set = set()
        # notify(kind, tokens): stream a parked/resumed control part to
        # the owning driver; installed per batch by the handler.
        self._notify: dict = {}        # token -> notify callable

    def register_notify(self, tokens, notify) -> None:
        with self._lock:
            for token in tokens:
                self._notify[token] = notify

    def forget_notify(self, tokens) -> None:
        with self._lock:
            for token in tokens:
                self._notify.pop(token, None)

    def sent(self, key, token) -> None:
        with self._lock:
            self._leases.setdefault(key, []).append(token)
            self._token_lease[token] = key
        # Stream a "started" mark to the owning driver: the frame is in
        # a worker's pipe, so from here on the task is MAYBE-STARTED —
        # if this daemon dies, the driver retries it under the
        # system-failure budget instead of requeueing it invisibly.
        self._fire(token, "started")

    def done(self, key, token) -> None:
        resumed = None
        with self._lock:
            order = self._leases.get(key)
            if order is None:
                return
            try:
                order.remove(token)
            except ValueError:
                pass
            self._token_lease.pop(token, None)
            self._parked.discard(token)
            if not order:
                self._leases.pop(key, None)
            elif order[0] in self._parked:
                # The next frame starts executing the moment this
                # reply was written: it is no longer parked.
                resumed = order[0]
                self._parked.discard(resumed)
        if resumed is not None:
            self._service.task_unblock(resumed)
            self._fire(resumed, "resumed")

    def drop_lease(self, key) -> None:
        """Lease died (worker crash): unpark everything it held —
        unstarted frames are requeued and re-tracked on a new lease."""
        with self._lock:
            order = self._leases.pop(key, [])
            parked = [t for t in order if t in self._parked]
            for token in order:
                self._token_lease.pop(token, None)
                self._parked.discard(token)
        for token in parked:
            self._service.task_unblock(token)
            self._fire(token, "resumed")

    def on_block(self, token) -> None:
        """A running task blocked in a nested get(): park every frame
        queued behind it on its lease."""
        with self._lock:
            key = self._token_lease.get(token)
            order = self._leases.get(key) if key is not None else None
            if not order or order[0] != token:
                return
            parked = [t for t in order[1:] if t not in self._parked]
            self._parked.update(parked)
        for queued in parked:
            self._service.task_block(queued)
            self._fire(queued, "parked")

    def _fire(self, token, kind: str) -> None:
        with self._lock:
            notify = self._notify.get(token)
        if notify is not None:
            try:
                notify(kind, token)
            except Exception:  # noqa: BLE001 — stream gone
                pass


class _ActorNewError(Exception):
    """Daemon-actor constructor failed; carries the serialized
    (exception, traceback) blob from the worker."""

    def __init__(self, blob: bytes):
        super().__init__("actor constructor failed")
        self.blob = blob


class _MuxPipe:
    """Multiplexed driver for an actor worker pipe in concurrent mode
    (max_concurrency > 1): calls are tagged with ids, a reader thread
    matches interleaved replies, and up to max_concurrency calls run
    worker-side simultaneously (reference: actor concurrency groups,
    transport/concurrency_group_manager.h)."""

    def __init__(self, conn):
        import queue as queue_mod

        self._queue_mod = queue_mod
        self._conn = conn
        self._send_lock = lock_witness.Lock("node_executor._MuxPipe.send")
        self._lock = lock_witness.Lock("node_executor._MuxPipe.state")
        self._pending: dict[int, Any] = {}
        self._next_id = 0
        self._closed = False
        threading.Thread(target=self._reader, daemon=True,
                         name="daemon-actor-mux-reader").start()

    def call(self, method: str, args_blob: bytes,
             n_returns: int) -> tuple:
        from ray_tpu.exceptions import WorkerCrashedError

        slot = self._queue_mod.SimpleQueue()
        with self._lock:
            if self._closed:
                raise WorkerCrashedError("actor process died")
            self._next_id += 1
            call_id = self._next_id
            self._pending[call_id] = slot
        try:
            with self._send_lock:
                self._conn.send(("actor_call_async", call_id, method,
                                 args_blob, n_returns))
        except (OSError, BrokenPipeError) as exc:
            with self._lock:
                self._pending.pop(call_id, None)
            raise WorkerCrashedError(
                f"actor pipe broken: {exc!r}") from exc
        result = slot.get()
        if result is None:
            raise WorkerCrashedError(
                "actor process died with the call in flight")
        return result

    def _reader(self) -> None:
        while True:
            try:
                msg = self._conn.recv()
            except (EOFError, OSError):
                break
            if msg[0] != "reply":
                continue
            _, call_id, status, payload = msg
            with self._lock:
                slot = self._pending.pop(call_id, None)
            if slot is not None:
                slot.put((status, payload))
        with self._lock:
            self._closed = True
            stranded = list(self._pending.values())
            self._pending.clear()
        for slot in stranded:
            slot.put(None)


class _DaemonActor:
    """A daemon-hosted actor: a dedicated worker process driven over
    its pipe (reference: a Ray actor IS a worker process with an
    ordered scheduling queue — core_worker.cc:2069 CreateActor lands
    the constructor in a leased worker; transport/actor_scheduling_
    queue.h orders the calls)."""

    def __init__(self, cls_blob: bytes, args_blob: bytes,
                 runtime_env: dict | None, max_concurrency: int,
                 extra_env: dict | None, allow_tpu: bool,
                 sys_path: list | None, worker=None):
        from ray_tpu._private.worker_pool import PoolWorker

        self.max_concurrency = max(1, int(max_concurrency or 1))
        self.owner: str | None = None  # creating driver's client addr
        # ``worker``: a prestarted standby process (reference:
        # worker_pool.h "Starts a number of workers ahead of time") —
        # creation then skips the fork on the critical path.
        self._worker = worker if worker is not None else PoolWorker(
            -1, extra_env=extra_env, allow_tpu=allow_tpu)
        self._mux = None
        reply = self._worker.request(
            ("actor_new", cls_blob, args_blob, runtime_env,
             self.max_concurrency, sys_path))
        if reply[0] == "err":
            self._worker.stop()
            raise _ActorNewError(reply[1])
        if self.max_concurrency > 1:
            self._mux = _MuxPipe(self._worker.conn)

    @property
    def pid(self) -> int:
        return self._worker.proc.pid

    def alive(self) -> bool:
        return self._worker.alive()

    def call(self, method: str, args_blob: bytes, n_returns: int) -> tuple:
        """-> ("ok", packed_list) | ("err", blob); raises
        WorkerCrashedError/_WorkerUnavailable on process death."""
        if self._mux is not None:
            return self._mux.call(method, args_blob, n_returns)
        return self._worker.request(
            ("actor_call", method, args_blob, n_returns))

    def kill(self) -> None:
        try:
            if self._worker.alive():
                self._worker.proc.terminate()
            # Always wait: an already-dead child must be reaped or it
            # stays a zombie for the daemon's lifetime.
            self._worker.proc.wait(timeout=2.0)
        except Exception:  # noqa: BLE001 — escalate
            self._worker.proc.kill()
            try:
                self._worker.proc.wait(timeout=2.0)
            except Exception:  # noqa: BLE001
                pass
        try:
            self._worker.conn.close()
        except OSError:
            pass  # worker pipe already torn down


class NodeExecutorService:
    """The daemon-side execution plane: worker pool + object store +
    the RPC surface (execute_task / actor plane / fetch_object /
    free_objects)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 pool_size: int | None = None,
                 resources: dict[str, float] | None = None):
        from ray_tpu._private.shm_store import ShmClient, ShmDirectory

        from ray_tpu._private.node_store_native import make_node_store

        self._server = RpcServer(host, port)
        # C++ store by default (reference: the raylet's object store is
        # native); Python fallback keeps identical semantics.
        self.store = make_node_store()
        self._peers = _PeerClients()
        # Watermark-driven spill tier (spill_manager.py): armed on the
        # Python store only (the managed tier needs the lease filter +
        # shm-twin/directory integration below; disarmed keeps the
        # legacy native/inline behavior byte-identically).
        from ray_tpu._private import spill_manager as _spill_mod

        self._spill_mgr = None
        # (owner, obj_hex, "spilled"|"restored") deltas pending the
        # next heartbeat's stats piggyback into the GCS directory.
        self._spill_events: list = []
        self._spill_events_lock = lock_witness.Lock(
            "node_executor.NodeExecutorService.spill_events")
        self.spilled_plan_hits = 0  # pulls whose plan flagged a spill
        if _spill_mod.SPILL_ON and isinstance(self.store,
                                              NodeObjectStore):
            self._spill_mgr = self.store.enable_managed_spill(
                leased_fn=self._spill_protected,
                on_spilled=self._on_blob_spilled,
                on_restored=self._on_blob_restored)
            # Admission's two-axis pressure classifier subtracts THIS
            # store's resident (spillable) bytes from host usage.
            from ray_tpu._private.memory_monitor import (
                set_store_bytes_provider,
            )

            set_store_bytes_provider(
                lambda: getattr(self.store, "_primary_bytes", 0))
        # P2P transfer plane: in-progress/relay pulls servable to peers
        # + the holder directory for objects THIS node owns.
        self._partials: dict[bytes, _PartialBlob] = {}
        self._partials_lock = lock_witness.Lock(
            "node_executor.NodeExecutorService.partials")
        self.chunk_directory = ChunkDirectory()
        self._advertised_address: str | None = None
        self.relay_chunks_served = 0  # cumulative, survives partial GC
        # Same-host zero-copy plane (same_host.py): co-hosted pullers
        # map this daemon's segments/arena instead of chunk-pulling.
        from ray_tpu._private.same_host import (
            LeaseTable,
            PeerArenaRegistry,
            host_identity,
        )

        self.host_id = host_identity()
        self.leases = LeaseTable()            # owner side: peers' pins
        self._peer_arenas = PeerArenaRegistry()  # puller side
        # key -> ("seg", seg_name, size): objects this daemon can serve
        # to same-host peers by name (owned segments only).
        self._map_sources: dict[bytes, tuple] = {}
        # Puller side: key -> (owner_addr, lease_token, seg|None) for
        # peer-owned mappings held by this daemon's shm-args cache.
        self._attached: dict[bytes, tuple] = {}
        # Data-plane path counters (map = zero-copy mapping handed out,
        # copy = single same-host memcpy, chunked = RPC chunk pull).
        self.same_host_map_hits = 0
        self.same_host_copy_hits = 0
        self.chunked_pulls = 0
        # Fault-path counters (executor_stats()["faults"]): peers/owners
        # blacklisted mid-pull and peer-owned mappings swept after their
        # owner died. fail-strike ledger for the attached-mapping sweep
        # (one transient probe miss must not drop a live owner's
        # mappings).
        self.peer_blacklists = 0
        self.lease_orphans_swept = 0
        self.arena_orphans_swept = 0
        # Overload-control counters: tasks refused because their
        # end-to-end deadline was already dead on arrival (daemon
        # admission or worker-frame pickup) and leases shed by the
        # queue-depth/memory-watermark admission caps.
        self.task_timeouts = 0
        self.admission_shed = 0
        self._attached_owner_strikes: dict[str, int] = {}
        # Worker-bound arg blobs promoted to shared memory: keyed by the
        # object's id bytes in the node's shm directory; FIFO-bounded.
        self._shm_args_lock = lock_witness.Lock(
            "node_executor.NodeExecutorService.shm_args")
        self._shm_args_order: list[tuple[bytes, int]] = []
        self._shm_args_bytes = 0
        # key -> monotonic stamp of the last worker-bound _ShmRef
        # hand-out: the spiller must not unlink a segment a dispatched
        # frame is about to attach (attach-after-unlink fails even
        # though existing mappings survive), so recently-out keys are
        # spill-protected for _SHM_ARG_GRACE_S.
        self._shm_out_stamp: dict[bytes, float] = {}
        self._resources = dict(resources or {})
        self._running_lock = lock_witness.Lock(
            "node_executor.NodeExecutorService.running")
        self._running: dict[str, dict[str, float]] = {}
        # token -> CPU share temporarily returned by a blocked task.
        self._blocked_cpu: dict[str, float] = {}
        self._func_cache: dict[str, Callable] = {}
        self._func_lock = lock_witness.Lock(
            "node_executor.NodeExecutorService.func")
        # Raw function blobs by digest: the batch path forwards these
        # to pool workers verbatim (the daemon never loads them).
        self._func_blob_cache: dict[str, bytes] = {}
        # need_func retries fetch their stashed args by nonce (bounded).
        self._stashed_args: dict[str, bytes] = {}
        # Pipelined execute path: per-lease frame ordering for
        # blocked-head parking + the per-stage drain counters.
        self._pipeline_inflight = _PipelineInflight(self)
        self.batch_rpcs = 0          # execute_task_batch calls served
        self.batch_tasks_received = 0
        self.reply_groups = 0        # grouped completion parts emitted
        # Fused in-daemon execution counters (FUSED_ON): runs executed
        # on the dispatch thread, tasks fused, and fused-eligible
        # entries that fell back to the worker pipeline because the
        # per-run wall budget expired.
        self.fused_runs = 0
        self.fused_tasks = 0
        self.fused_fallbacks = 0
        # Persistent batch runners: long-lived threads fed by a queue
        # replace the old thread-per-batch spawn — steady-state
        # execution allocates zero threads (reuses >> spawns).
        from ray_tpu._private.rpc import _ThreadRecycler

        self._batch_runners = _ThreadRecycler("exec-batch-runner",
                                              idle_s=30.0)
        # Driver import paths adopted via adopt_sys_path; forwarded to
        # pool workers with each task so by-reference pickles resolve.
        self._driver_sys_path: list[str] = []
        self.tasks_executed = 0
        # Speculation loser-cancel tokens (cancel_task RPC): checked
        # before a task's user function runs — a straggler still held
        # in admission (or a chaos sched.straggle delay) whose sibling
        # copy already sealed provably never executes. Bounded FIFO.
        self._cancel_lock = lock_witness.Lock(
            "node_executor.NodeExecutorService.cancel")
        self._cancelled_tokens: "collections.OrderedDict" = \
            collections.OrderedDict()
        # Fired (outside the ledger lock) whenever admission state
        # changes; the NodeAgent hooks this to push a syncer update
        # instead of waiting out the heartbeat period (reference: the
        # ray_syncer streams deltas on change, ray_syncer.h:88).
        self._load_listener: Callable[[], None] | None = None
        # Actor plane: actor key (bytes) -> _DaemonActor.
        self._actors: dict[bytes, _DaemonActor] = {}
        self._actors_lock = lock_witness.Lock(
            "node_executor.NodeExecutorService.actors")
        # Creation gate: keys whose constructor is in flight. An
        # actor_call declaring awaiting_create waits here instead of
        # bouncing "gone" — the driver pipelines __init__ with the
        # first method call(s) and the daemon orders them.
        self._actors_creating: set[bytes] = set()
        self._actors_creating_cond = threading.Condition(
            self._actors_lock)
        # Prestarted standby workers for actor creation, keyed by the
        # spawn-relevant env (client addr); refilled asynchronously so
        # forks overlap RPC waits instead of sitting on the creation
        # critical path.
        self._standby: dict[tuple, list] = {}
        self._standby_lock = lock_witness.Lock(
            "node_executor.NodeExecutorService.standby")
        self._standby_refilling: set[tuple] = set()
        self._standby_target = 2
        self._stop_event = threading.Event()
        self._sweep_thread: threading.Thread | None = None

        if pool_size is None:
            pool_size = max(1, min(int(self._resources.get(
                "CPU", os.cpu_count() or 1)), 16))
        from ray_tpu._private.worker_pool import WorkerPool

        self._shm_directory = ShmDirectory()
        self._shm_client = ShmClient()
        self.pool = WorkerPool(pool_size, self._shm_directory,
                               self._shm_client)

        s = self._server
        s.register("ping", lambda: "pong")
        s.register("exec_ping", lambda: os.getpid())
        # Long-running methods dispatch concurrently so ONE multiplexed
        # connection carries all of a driver's in-flight work (reference:
        # async completion queues, client_call.h — not a socket per task).
        s.register("execute_task", self.execute_task, concurrent=True)
        s.register("execute_task_batch", self.execute_task_batch,
                   concurrent=True, streaming=True)
        s.register("fetch_object", self.fetch_object,
                   concurrent="pooled")
        s.register("fetch_plan", self.fetch_plan, concurrent="pooled")
        s.register("unpin_object", self.unpin_object)
        s.register("free_objects", self.free_objects)
        s.register("executor_stats", self.executor_stats)
        s.register("flight_ring", self._flight_ring)
        s.register("configure_perf", self._configure_perf)
        s.register("cancel_task", self.cancel_task)
        s.register("task_block", self.task_block)
        s.register("task_unblock", self.task_unblock)
        s.register("adopt_sys_path", self.adopt_sys_path)
        s.register("create_actor", self.create_actor, concurrent=True)
        s.register("actor_call", self.actor_call, concurrent=True)
        s.register("actor_kill", self.actor_kill)

    @property
    def port(self) -> int:
        return self._server.port

    def address_for(self, host: str) -> str:
        return f"{host}:{self._server.port}"

    @property
    def advertised_address(self) -> str:
        """The address peers reach this executor at — what this node
        registers in owners' chunk directories when pulling."""
        if self._advertised_address is None:
            from ray_tpu._private.node import _own_address

            self._advertised_address = self.address_for(_own_address())
        return self._advertised_address

    @advertised_address.setter
    def advertised_address(self, value: str) -> None:
        self._advertised_address = value

    def start(self) -> "NodeExecutorService":
        self._server.start()
        from ray_tpu._private.config import GLOBAL_CONFIG

        period_ms = int(GLOBAL_CONFIG.owner_sweep_period_ms or 0)
        if period_ms > 0:
            self._sweep_thread = threading.Thread(
                target=self._owner_sweep_loop,
                args=(period_ms / 1000.0,
                      float(GLOBAL_CONFIG.owner_dead_grace_s)),
                daemon=True, name="node-owner-sweep")
            self._sweep_thread.start()
        return self

    def _owner_sweep_loop(self, period_s: float, grace_s: float) -> None:
        """Owner-death GC: a driver whose client endpoint stays
        unreachable past the grace period has crashed — drop its primary
        blobs and kill its actors, or a dead driver's results pin daemon
        memory forever (reference: owner-death cleanup in the ownership
        protocol, reference_count.h:61; actor owners dying kill their
        actors, gcs_actor_manager.h)."""
        import time as _time
        from concurrent.futures import ThreadPoolExecutor

        # Sweep requires SUSTAINED unreachability: fail_since records the
        # first of an unbroken run of failed probes; one transient miss
        # (dropped SYN, a slow driver tick) never frees a live owner's
        # state. Probes run concurrently so many dead owners cannot
        # stretch the sweep period and starve probes of live ones.
        fail_since: dict[str, float] = {}
        while not self._stop_event.wait(period_s):
            self._sweep_transfer_plane()
            with self._actors_lock:
                actor_owners = {a.owner: None for a in
                                self._actors.values()
                                if getattr(a, "owner", None)}
            owners = set(self.store.owners()) | set(actor_owners)
            if not owners:
                fail_since.clear()
                continue

            def probe_one(owner: str) -> bool:
                try:
                    probe = RpcClient(owner, timeout_s=3.0,
                                      connect_timeout_s=2.0)
                    try:
                        return probe.call("ping") == "pong"
                    finally:
                        probe.close()
                except Exception:  # noqa: BLE001 — unreachable
                    return False

            with ThreadPoolExecutor(max_workers=min(8, len(owners))) \
                    as pool:
                results = dict(zip(owners, pool.map(probe_one, owners)))
            now = _time.monotonic()
            for owner, alive in results.items():
                if alive:
                    fail_since.pop(owner, None)
                    continue
                first_fail = fail_since.setdefault(owner, now)
                if now - first_fail <= grace_s:
                    continue
                freed = self.store.free_owner(owner)
                with self._actors_lock:
                    dead_keys = [k for k, a in self._actors.items()
                                 if getattr(a, "owner", None) == owner]
                for key in dead_keys:
                    self._reap_actor(key)
                fail_since.pop(owner, None)
                if freed or dead_keys:
                    import logging

                    logging.getLogger("ray_tpu").warning(
                        "owner %s unreachable for %.0fs: swept %d blobs,"
                        " %d actors", owner, grace_s, freed,
                        len(dead_keys))
            for owner in list(fail_since):
                if owner not in owners:
                    del fail_since[owner]

    def stop(self) -> None:
        self._stop_event.set()
        self._server.stop()
        if self._spill_mgr is not None:
            self._spill_mgr.stop()
        # Same-host plane: drop owner-side pins (peers' leases) and
        # this daemon's peer mappings before the directories unwind.
        self.leases.clear()
        with self._shm_args_lock:
            attached = list(self._attached.values())
            self._attached.clear()
            self._map_sources.clear()
        for _, _, seg in attached:
            if seg is not None:
                try:
                    seg.close()
                except (BufferError, OSError):
                    pass  # exported buffers pin the map; tracker reaps
        self._peer_arenas.close_all()
        with self._actors_lock:
            actors = list(self._actors.values())
            self._actors.clear()
        for actor in actors:
            actor.kill()
        with self._standby_lock:
            standby = [w for pool in self._standby.values()
                       for w in pool]
            self._standby.clear()
        for worker in standby:
            worker.stop()
        self.pool.shutdown()
        self._peers.close()
        # Relay partials view shm segments; release the views before
        # the directory unlinks/closes them.
        with self._partials_lock:
            parts, self._partials = list(self._partials.values()), {}
        for part in parts:
            if part.external:
                with part.lock:
                    try:
                        part.buf.release()
                    except BufferError:
                        pass
        self._shm_client.close_all()
        self._shm_directory.shutdown()
        if hasattr(self.store, "close"):
            self.store.close()  # native store: free the C++ handle

    # ------------------------------------------------------------- endpoints

    def execute_task(self, digest: str, func_blob: bytes | None,
                     args_blob: bytes, n_returns: int,
                     return_keys: list[bytes],
                     runtime_env: dict | None = None,
                     resources: dict | None = None,
                     task_token: str | None = None,
                     client_addr: str | None = None,
                     args_ref: str | None = None,
                     trace_ctx: tuple | None = None,
                     deadline: float | None = None) -> tuple:
        """Run one task; reply ("ok", [result descriptors]) where each
        descriptor is ("inline", blob) or ("stored", size), or
        ("need_func", nonce) when the digest is unknown here (args are
        stashed under the nonce so the retry ships the function alone),
        or ("err", exc_blob).

        ``trace_ctx`` (trace_id, parent span_id, anchor): the driver is
        tracing this task — stamp daemon-side stage timestamps, open a
        linked span, and piggyback both (plus any buffered spans) on
        the reply as a third tuple element. The context's presence IS
        the enable signal; without it this path costs nothing."""
        # Admission: with several drivers sharing this node, each one
        # accounts only its own leases — reject work beyond capacity and
        # let the submitter spill to another node (reference: raylet
        # spillback, cluster_task_manager.h:42 / HandleRequestWorkerLease
        # redirecting the lease).
        # The reservation is keyed by the driver's task token so a task
        # blocked in a nested get() can return its CPU (task_block /
        # task_unblock, driven by the owning driver's block context —
        # reference: workers blocked in ray.get return their CPU to the
        # raylet).
        self._warm_factory_once()
        demand = dict(resources or {})
        demand.setdefault("CPU", 1.0)
        token = task_token or f"exec-{digest[:8]}-{os.urandom(4).hex()}"
        if args_blob is None and args_ref is not None:
            with self._func_lock:
                args_blob = self._stashed_args.pop(args_ref, None)
            if args_blob is None:
                return ("stale_args",)
        if deadline is not None and time.time() > deadline:
            # End-to-end budget already dead on arrival: refuse the
            # lease — the driver seals TaskTimeoutError, nothing runs.
            self.task_timeouts += 1
            return ("timeout", "admitted")
        shed_why = self._overload_reason()
        if shed_why is not None:
            self.admission_shed += 1
            return ("overloaded", shed_why)
        if not self._try_reserve(token, demand):
            return ("busy",)
        # ``trace_stages`` doubles as the always-on perf-plane carrier:
        # traced tasks get the full admitted/worker/exec stamp chain,
        # perf-armed untraced tasks a bare dict that only collects the
        # worker's pickup stamp + resource sample (no span machinery).
        perf_on = perf.PERF_ON
        t_admit = time.time() if (trace_ctx is not None or perf_on) \
            else 0.0
        trace_stages = {"admitted": t_admit} \
            if trace_ctx is not None else ({} if perf_on else None)
        try:
            from ray_tpu._private import chaos

            if chaos.ACTIVE is not None \
                    and chaos.ACTIVE.should("sched.straggle"):
                # One slow node: the delay sits BEFORE the user
                # function, so a speculation loser-cancel landing
                # mid-delay provably prevents the execution.
                self._chaos_straggle(task_token)
            if self._token_cancelled(task_token):
                # Speculation first-seal-wins: a sibling copy already
                # sealed and the driver cancelled this token before we
                # ran anything — refuse without executing.
                return ("cancelled",)
            with self._func_lock:
                func = self._func_cache.get(digest)
                if func_blob is not None:
                    # Raw blob kept for the batch path's pool forwards.
                    self._func_blob_cache[digest] = func_blob
            if func is None:
                if func_blob is None:
                    # Stash the args so the retry ships the function
                    # alone (never re-sends possibly-large args). Bounded
                    # by entries AND bytes: a driver that dies between
                    # the two calls must not pin blobs here forever.
                    nonce = os.urandom(8).hex()
                    with self._func_lock:
                        self._stashed_args[nonce] = args_blob
                        total = sum(len(b) for b in
                                    self._stashed_args.values())
                        while self._stashed_args and (
                                len(self._stashed_args) > 256
                                or total > 256 * 1024 * 1024):
                            victim = next(iter(self._stashed_args))
                            total -= len(self._stashed_args.pop(victim))
                    return ("need_func", nonce)
                # Deserialize OUTSIDE the lock: loading can import heavy
                # modules and must not stall other tasks' cache lookups.
                try:
                    func = serialization.loads_function(func_blob)
                except BaseException as exc:  # noqa: BLE001
                    return ("err", _exc_blob(exc))
                with self._func_lock:
                    self._func_cache[digest] = func
            args, kwargs = serialization.deserialize_from_buffer(
                memoryview(args_blob))
            # CPU tasks execute in pool workers: hand large args over
            # as shared-memory descriptors, not re-serialized payloads.
            on_pool = not any(k.startswith("TPU")
                              for k in (resources or {}))
            args, kwargs = self._resolve_fetch_args(args, kwargs,
                                                    to_shm=on_pool)
            if trace_stages is None:
                values = self._run(func, digest, func_blob, args,
                                   kwargs, n_returns, runtime_env,
                                   resources or {}, task_token=token,
                                   client_addr=client_addr)
            elif trace_ctx is None:
                # Perf-armed, tracing off: thread the stages dict so
                # the pool reply's pickup stamp + resource sample land
                # here, without any span/trace-payload work.
                t_exec = time.time()
                values = self._run(func, digest, func_blob, args,
                                   kwargs, n_returns, runtime_env,
                                   resources or {}, task_token=token,
                                   client_addr=client_addr,
                                   trace_stages=trace_stages)
                trace_stages.setdefault("exec_start", t_exec)
                trace_stages.setdefault("exec_end", time.time())
            else:
                from ray_tpu.util import tracing

                t_exec = time.time()
                with tracing.remote_span(
                        "daemon:execute", trace_ctx, _proc_label(),
                        {"digest": digest[:8]}):
                    values = self._run(func, digest, func_blob, args,
                                       kwargs, n_returns, runtime_env,
                                       resources or {},
                                       task_token=token,
                                       client_addr=client_addr,
                                       trace=trace_ctx,
                                       trace_stages=trace_stages)
                # Pool-worker runs reported their own (finer) stamps
                # into trace_stages; in-daemon runs (TPU tasks) get the
                # daemon-level envelope.
                trace_stages.setdefault("exec_start", t_exec)
                trace_stages.setdefault("exec_end", time.time())
                wpid = trace_stages.pop("pid", None)
                if wpid is not None and "exec_start" in trace_stages \
                        and "exec_end" in trace_stages:
                    tracing.buffer_span({
                        "name": "worker:execute",
                        "span_id": os.urandom(8).hex(),
                        "parent_id": trace_ctx[1],
                        "trace_id": trace_ctx[0],
                        "start_time": trace_stages["exec_start"],
                        "end_time": trace_stages["exec_end"],
                        "thread": "task",
                        "proc": f"worker:{wpid}",
                        "attributes": {"token": token},
                    })
        except BaseException as exc:  # noqa: BLE001 — shipped to driver
            return ("err", _exc_blob(exc))
        finally:
            with self._running_lock:
                self._running.pop(token, None)
                self._blocked_cpu.pop(token, None)
            self._notify_load()
        self.tasks_executed += 1
        if perf_on and trace_stages is not None:
            self._record_task_perf(trace_stages, t_admit)

        out = []
        for id_bytes, value in zip(return_keys, values):
            try:
                blob = serialization.serialize_framed(value)
            except BaseException as exc:  # noqa: BLE001
                out.append(("err", _exc_blob(exc)))
                continue
            if len(blob) <= _inline_reply_bytes():
                out.append(("inline", blob))
            else:
                self.store.put(id_bytes, blob, owner=client_addr)
                self._maybe_export_stored(id_bytes, blob)
                out.append(("stored", len(blob)))
        if trace_ctx is not None:
            return ("ok", out, self._trace_payload(trace_stages))
        return ("ok", out)

    def _record_task_perf(self, stages: dict, t_admit: float) -> None:
        """Always-on plane: fold one finished task's stamps into this
        daemon's stage histograms and attribution table. Pops the
        worker's resource sample so traced replies never ship it to the
        driver (resources roll up per node, not per task event)."""
        sample = stages.pop("perf", None)
        pickup = stages.get("worker_start") or stages.get("exec_start")
        if pickup and t_admit:
            perf.record_stage("admit_worker", max(0.0, pickup - t_admit))
        if sample is not None:
            try:
                perf.record_task_resources(sample[0], sample[1],
                                           sample[2], sample[3])
                perf.record_stage("exec", float(sample[1]))
                return
            except (TypeError, IndexError):
                pass
        exec_start = stages.get("exec_start")
        exec_end = stages.get("exec_end")
        if exec_start and exec_end:
            # In-daemon run (TPU task) or a worker without the plane:
            # the daemon-level envelope is the exec wall.
            perf.record_stage("exec", max(0.0, exec_end - exec_start))

    def _flight_ring(self) -> dict:
        """Live post-mortem surface for ``ray_tpu debug``: this
        process's flight-recorder ring plus the fault/breaker/stage
        state the dumped ring files carry."""
        from ray_tpu._private import flight_recorder
        from ray_tpu._private.rpc import breaker_stats

        rec = flight_recorder.get()
        snap = rec.snapshot() if rec is not None else {
            "role": _proc_label(), "pid": os.getpid(), "events": []}
        snap.setdefault("fault_stats", self._fault_stats())
        snap.setdefault("breaker", breaker_stats())
        snap.setdefault("spill", self._spill_stats())
        snap.setdefault("stage_hist", perf.stage_snapshot())
        return snap

    def _configure_perf(self, on: bool) -> bool:
        """Arm/disarm this daemon's always-on plane at runtime (the
        overhead-calibration seam bench_envelope drives)."""
        (perf.enable if on else perf.disable)()
        return perf.PERF_ON

    def _trace_payload(self, stages: dict) -> dict:
        """Reply piggyback: this task's daemon-clock stage stamps, any
        buffered spans (this task's + orphans), and the daemon wall
        clock NOW — the driver's ClockSync anchors its half-RTT offset
        on it so merged timelines line up."""
        from ray_tpu.util import tracing

        return {"stages": stages, "spans": tracing.drain_buffered(),
                "now": time.time()}

    def _maybe_export_stored(self, id_bytes: bytes, blob) -> None:
        """Give a large stored primary a named-segment twin so
        same-host consumers (peer daemons, the driver) map it instead
        of chunk-pulling. One memcpy here buys zero copies per
        consumer; bounded by the shm-args FIFO cache."""
        from ray_tpu._private.same_host import map_enabled, map_min_bytes

        if not map_enabled() or len(blob) < map_min_bytes():
            return
        with self._shm_args_lock:
            if self._shm_directory.lookup(id_bytes) is not None:
                return
        from multiprocessing import shared_memory

        try:
            seg = shared_memory.SharedMemory(create=True,
                                             size=max(len(blob), 1))
        except OSError:
            return  # /dev/shm full: chunked fallback still serves
        seg.buf[:len(blob)] = blob
        self._register_shm_arg(id_bytes, seg, len(blob))

    _SHM_ARG_GRACE_S = 30.0

    def _spill_protected(self) -> set:
        """Ids the spiller must skip: same-host peers' lease pins plus
        keys whose worker-bound _ShmRef went out within the grace
        window (their frames may not have attached the segment yet)."""
        out = set(self.leases.pinned_ids())
        now = time.monotonic()
        with self._shm_args_lock:
            for key in [k for k, at in self._shm_out_stamp.items()
                        if now - at > self._SHM_ARG_GRACE_S]:
                del self._shm_out_stamp[key]
            out.update(self._shm_out_stamp)
        return out

    def _on_blob_spilled(self, key: bytes, owner: str | None) -> None:
        """A primary moved to the disk tier: free its shm/arena twin
        (the spiller's victim filter already excluded leased ids, so
        no same-host peer holds a pin; POSIX keeps already-mapped
        segments valid past the unlink) and queue the spilled-location
        delta for the next heartbeat's directory piggyback."""
        self._drop_shm_arg(key)
        if owner:
            with self._spill_events_lock:
                self._spill_events.append((owner, key.hex(), "spilled"))
                del self._spill_events[:-4096]  # bounded

    def _on_blob_restored(self, key: bytes, owner: str | None) -> None:
        """A spilled primary is back in memory: the node never left the
        holder set, so clearing the directory's spill mark IS the
        re-registration (the shm twin rebuilds lazily on the next
        worker-bound fetch via _blob_to_shm)."""
        if owner:
            with self._spill_events_lock:
                self._spill_events.append((owner, key.hex(), "restored"))
                del self._spill_events[:-4096]

    def _drain_spill_events(self) -> list:
        with self._spill_events_lock:
            out, self._spill_events = self._spill_events, []
        return out

    def set_load_listener(self, listener: Callable[[], None]) -> None:
        self._load_listener = listener

    def _notify_load(self) -> None:
        listener = self._load_listener
        if listener is not None:
            try:
                listener()
            except Exception:  # noqa: BLE001 — sync is best-effort
                pass

    def cancel_task(self, token: str) -> bool:
        """Speculation loser-cancel: flag ``token`` so an execution
        that hasn't reached its user function yet refuses with
        ("cancelled",) instead of running (first-seal-wins — the
        winner's value is already sealed driver-side). Best-effort: a
        task already executing completes normally and its reseal is
        skipped by the driver's claim_win gate."""
        with self._cancel_lock:
            self._cancelled_tokens[token] = True
            while len(self._cancelled_tokens) > 4096:
                self._cancelled_tokens.popitem(last=False)
        return True

    def _token_cancelled(self, token: "str | None") -> bool:
        if token is None:
            return False
        with self._cancel_lock:
            return self._cancelled_tokens.pop(token, None) is not None

    def _chaos_straggle(self, token: "str | None") -> None:
        """sched.straggle chaos site: artificially delay this node's
        exec (making straggler-speculation triggers deterministic in
        tests/benches). Sleeps in short slices so a loser-cancel
        arriving mid-delay aborts the wait — the straggler then
        provably never runs its user function."""
        total = float(os.environ.get("RAY_TPU_STRAGGLE_S", "2.0"))
        deadline = time.monotonic() + total
        while time.monotonic() < deadline:
            if token is not None:
                with self._cancel_lock:
                    if token in self._cancelled_tokens:
                        return  # popped by the caller's cancel check
            time.sleep(0.05)

    def _overload_reason(self) -> "str | None":
        """Why admission should SHED (not merely spill) right now:
        the overload.saturate chaos site, the admitted-reservation
        depth cap, or the host-memory watermark. None = admit
        normally. One seeded chaos draw per call — callers check once
        per RPC/batch, keeping injection deterministic."""
        from ray_tpu._private import chaos

        if chaos.ACTIVE is not None \
                and chaos.ACTIVE.should("overload.saturate"):
            return "chaos: overload.saturate"
        from ray_tpu._private.config import GLOBAL_CONFIG

        cap = int(GLOBAL_CONFIG.admission_max_queue_depth or 0)
        if cap > 0:
            with self._running_lock:
                depth = len(self._running)
            if depth >= cap:
                return (f"admitted reservations at "
                        f"admission_max_queue_depth={cap}")
        watermark = float(
            GLOBAL_CONFIG.admission_memory_watermark or 0)
        if watermark > 0:
            from ray_tpu._private import spill_manager as _spill_mod
            from ray_tpu._private.memory_monitor import (
                memory_pressure_kind,
                memory_watermark_exceeded,
            )

            if _spill_mod.SPILL_ON and self._spill_mgr is not None:
                # Two-axis classification: STORE pressure is
                # recoverable — kick the spiller and admit (degrade to
                # disk, not to failure) — unless disk-full backoff
                # means spilling cannot relieve it, which falls
                # through to the typed shed exactly like true HOST
                # pressure.
                kind = memory_pressure_kind(watermark)
                if kind == "store":
                    if not self._spill_mgr.backing_off():
                        self._spill_mgr.request_spill()
                        kind = None
                    else:
                        return ("store memory over admission_memory_"
                                f"watermark={watermark} and the spill "
                                "disk is full (backing off)")
                if kind == "host":
                    return (f"host memory over admission_memory_"
                            f"watermark={watermark}")
            elif memory_watermark_exceeded(watermark):
                # Disarmed tier: the PR-7 single-axis shed, unchanged.
                return (f"host memory over admission_memory_watermark"
                        f"={watermark}")
        return None

    def _try_reserve(self, token: str, demand: dict) -> bool:
        """Admission: reserve ``demand`` under ``token`` atomically with
        the capacity check (two concurrent calls must not both pass a
        half-full node) — shared by tasks and actors (reference: raylet
        admission before the lease grant, cluster_task_manager.h:42)."""
        with self._running_lock:
            for key, cap in self._resources.items():
                used = sum(float(d.get(key, 0.0))
                           for d in self._running.values())
                if used + float(demand.get(key, 0.0)) > float(cap) + 1e-9:
                    return False
            self._running[token] = demand
        self._notify_load()
        return True

    def _try_reserve_many(self, wants: list) -> list[bool]:
        """Batched admission: one lock pass reserves every entry that
        fits (per-entry accept/reject — a saturating batch admits its
        prefix and the rest spill, exactly like per-task admission)."""
        out = []
        with self._running_lock:
            for token, demand in wants:
                ok = True
                for key, cap in self._resources.items():
                    used = sum(float(d.get(key, 0.0))
                               for d in self._running.values())
                    if used + float(demand.get(key, 0.0)) \
                            > float(cap) + 1e-9:
                        ok = False
                        break
                if ok:
                    self._running[token] = demand
                out.append(ok)
        if any(out):
            self._notify_load()
        return out

    @staticmethod
    def _needs_dedicated_worker(runtime_env: dict | None) -> bool:
        """Entries whose runtime_env demands a fresh interpreter
        (containers, import-sensitive jax/XLA env vars) cannot ride a
        shared pipelined lease."""
        if not runtime_env:
            return False
        if runtime_env.get("container"):
            return True
        from ray_tpu._private.worker_pool import WorkerPool

        return bool(WorkerPool._import_sensitive_env_vars(runtime_env))

    def _pipe_reply_to_task_reply(self, return_keys: list, status: str,
                                  payload, owner: str | None) -> tuple:
        """Worker-pipe batch completion -> the execute_task per-task
        reply shape. Inline worker results are already framed blobs, so
        small results cross daemon-side with ZERO deserialize/
        re-serialize passes (the classic path pays both)."""
        from ray_tpu.exceptions import WorkerCrashedError

        if status == "timeout":
            # The worker found the frame's deadline dead at pickup
            # (budget died queued behind the lease head): typed refusal,
            # nothing executed.
            self.task_timeouts += 1
            return ("timeout", "worker")
        if status == "crash":
            from ray_tpu._private import flight_recorder

            flight_recorder.record("worker.crash", str(payload)[:120])
            # Normalize to WorkerCrashedError (the payload may be a
            # pool-internal _WorkerUnavailable) so the driver's retry
            # policy recognizes the system failure.
            if isinstance(payload, WorkerCrashedError):
                exc = payload
            else:
                exc = WorkerCrashedError(str(payload))
                exc.__cause__ = payload if isinstance(
                    payload, BaseException) else None
            return ("err", _exc_blob(exc))
        if status == "err":
            return ("err", payload)
        out = []
        for id_bytes, packed in zip(return_keys, payload):
            if packed[0] == "inline":
                blob = packed[1]
            else:
                blob = self._packed_to_blob(id_bytes, packed)
                if blob is None:
                    out.append(packed)  # ("err", blob) passthrough
                    continue
            if len(blob) <= _inline_reply_bytes():
                out.append(("inline", blob))
            else:
                self.store.put(id_bytes, blob, owner=owner)
                self._maybe_export_stored(id_bytes, blob)
                out.append(("stored", len(blob)))
        self.tasks_executed += 1
        return ("ok", out)

    def execute_task_batch(self, entries: list,
                           client_addr: str | None = None,
                           _emit_part=None) -> tuple:
        """Run a batch of tasks leased to this node in one RPC,
        streaming grouped completions back as they finish (no barrier
        on the slowest task).

        Each entry: (digest, func_blob, args_blob, n_returns,
        return_keys, runtime_env, resources, task_token, flags) with
        flags bit 0 = args contain FetchRef placeholders. Ref-bearing,
        TPU and dedicated-env entries take the classic per-task path on
        their own dispatch threads; everything else fans across
        pipelined multi-task worker leases (worker_pool.run_task_batch).

        Streamed parts: ("results", [(idx, reply), ...]) with the
        execute_task reply shape per task, plus ("parked", idx) /
        ("resumed", idx) control parts when frames queue behind a
        blocked lease head or an over-subscribed entry waits in daemon
        admission, and ("started", idx) before an entry can first
        side-effect. Final reply: ("done", n, fused_stats).

        While FUSED_ON, a run of eligible entries (no refs, no TPU, no
        runtime_env) executes directly on this dispatch thread — no
        worker-pipe hop — under the fused_max_run_tasks /
        fused_run_wall_budget_s budget; the remainder falls back to the
        pipelined worker path. Entries the driver over-subscribed
        beyond this node's free slots (flags bit 2) PARK in daemon
        admission when the reservation fails — completions free
        capacity and re-admit them — instead of bouncing ("busy",)
        spillbacks per slot."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.rpc import DISPATCH_POOL
        from ray_tpu._private.worker_pool import _BatchTask

        self._warm_factory_once()
        from ray_tpu._private import chaos as _chaos

        if _chaos.ACTIVE is not None \
                and _chaos.ACTIVE.should("sched.straggle"):
            # Slow-node chaos: one delay per batch RPC (the per-token
            # cancel-aware slicing lives on the single-task path).
            time.sleep(float(os.environ.get("RAY_TPU_STRAGGLE_S",
                                            "2.0")))
        if type(entries) is tuple and entries and entries[0] == "col1":
            # Columnar batch descriptor (driver dispatch lanes): one
            # shared (digest, resources) header + parallel args/key
            # columns instead of a 9-tuple per task.
            return self._execute_columnar(entries, client_addr,
                                          _emit_part)
        self.batch_rpcs += 1
        self.batch_tasks_received += len(entries)
        n = len(entries)
        cond = lock_witness.Condition(
            "node_executor.batch_wait", plain_lock=True)
        completions: list = []
        control: list = []

        def complete(idx: int, reply: tuple) -> None:
            with cond:
                completions.append((idx, reply))
                cond.notify()

        with self._func_lock:
            sys_path = list(self._driver_sys_path) or None
        pipeline: list[_BatchTask] = []
        fused: list[_BatchTask] = []
        # Over-subscribed entries whose reservation failed, waiting for
        # capacity: [(task, demand)] — drained by the reply loop.
        parked: list = []
        reserve_wants: list = []
        demand_by_idx: dict[int, dict] = {}
        token_idx: dict[str, int] = {}
        # One shed decision per batch RPC (one chaos draw; depth and
        # watermark barely move within a batch): under overload the
        # whole batch sheds — the driver fails deadline-armed entries
        # fast and spillback-requeues the rest.
        shed_why = self._overload_reason()
        now = time.time()
        fused_cap = (max(1, int(GLOBAL_CONFIG.fused_max_run_tasks))
                     if FUSED_ON else 0)
        for idx, entry in enumerate(entries):
            (digest, func_blob, args_blob, n_returns, return_keys,
             runtime_env, resources, token, flags) = entry[:9]
            # Optional 10th/11th elements: the driver's trace context
            # and the absolute end-to-end deadline for this entry
            # (absent ⇒ off for it — zero cost).
            trace_ctx = entry[9] if len(entry) > 9 else None
            deadline = entry[10] if len(entry) > 10 else None
            if func_blob is not None:
                with self._func_lock:
                    self._func_blob_cache[digest] = func_blob
            if deadline is not None and now > deadline:
                self.task_timeouts += 1
                complete(idx, ("timeout", "admitted"))
                continue
            if shed_why is not None:
                self.admission_shed += 1
                complete(idx, ("overloaded", shed_why))
                continue
            demand = dict(resources or {})
            demand.setdefault("CPU", 1.0)
            token = token or f"exec-{digest[:8]}-{os.urandom(4).hex()}"
            classic = ((flags & 1)
                       or any(k.startswith("TPU") for k in demand)
                       or self._needs_dedicated_worker(runtime_env))
            if classic:
                def classic_run(idx=idx, digest=digest,
                                func_blob=func_blob,
                                args_blob=args_blob, n_returns=n_returns,
                                return_keys=return_keys,
                                runtime_env=runtime_env,
                                resources=resources, token=token,
                                trace_ctx=trace_ctx, deadline=deadline):
                    try:
                        reply = self.execute_task(
                            digest, func_blob, args_blob, n_returns,
                            return_keys, runtime_env, resources, token,
                            client_addr, trace_ctx=trace_ctx,
                            deadline=deadline)
                    except BaseException as exc:  # noqa: BLE001
                        reply = ("err", _exc_blob(exc))
                    complete(idx, reply)

                # Classic entries begin executing the moment they are
                # submitted: mark them maybe-started for the driver's
                # death accounting before the dispatch.
                with cond:
                    control.append(("started", idx))
                    cond.notify()
                DISPATCH_POOL.submit(classic_run)
                continue
            blob = func_blob
            if blob is None:
                with self._func_lock:
                    blob = self._func_blob_cache.get(digest)
            if blob is None:
                # Daemon restarted since the driver learned the digest:
                # that task retries via the single execute path.
                complete(idx, ("need_func", None))
                continue
            token_idx[token] = idx
            demand_by_idx[idx] = demand
            task = _BatchTask(
                idx=idx, digest=digest, func_blob=blob,
                args_blob=args_blob, n_returns=max(1, n_returns),
                runtime_env=runtime_env, token=token,
                client_addr=client_addr, sys_path=sys_path,
                trace=trace_ctx, deadline=deadline,
                overcommit=bool(flags & 2), return_keys=return_keys)
            if len(fused) < fused_cap and not runtime_env \
                    and not (flags & 8):
                # Fused-eligible: executes on this dispatch thread, no
                # per-entry reservation (the run is one serial thread).
                # Flags bit 3 (no-fuse) marks a columnar run's budget
                # spill: it must ride the worker pipeline so the
                # dispatch thread stays free to stream replies.
                fused.append(task)
                continue
            reserve_wants.append((task, demand))
        admit_ts: dict[int, float] = {}
        return_keys_by_idx = {t.idx: entries[t.idx][4] for t in fused}
        for task, _ in reserve_wants:
            return_keys_by_idx[task.idx] = entries[task.idx][4]

        def notify(kind: str, token: str) -> None:
            with cond:
                control.append((kind, token_idx.get(token)))
                cond.notify()

        def on_result(task, status, payload, wtrace=None):
            with self._running_lock:
                self._running.pop(task.token, None)
                self._blocked_cpu.pop(task.token, None)
            if wtrace and perf.PERF_ON:
                # Always-on plane: the worker's pickup stamp and
                # resource sample ride the reply whether or not
                # tracing armed this task.
                self._record_task_perf(wtrace,
                                       admit_ts.get(task.idx, 0.0))
            try:
                reply = self._pipe_reply_to_task_reply(
                    return_keys_by_idx[task.idx], status, payload,
                    client_addr)
            except BaseException as exc:  # noqa: BLE001
                reply = ("err", _exc_blob(exc))
            if task.trace is not None and reply[0] == "ok":
                reply = (reply[0], reply[1], self._batch_trace(
                    task, admit_ts.get(task.idx), wtrace))
            complete(task.idx, reply)

        notified_tokens: list = []

        def launch(run_tasks: "list[_BatchTask]") -> None:
            tokens = [t.token for t in run_tasks]
            self._pipeline_inflight.register_notify(tokens, notify)
            notified_tokens.extend(tokens)
            depth = max(1, int(GLOBAL_CONFIG.worker_pipeline_depth))
            # Persistent runner threads (LIFO-recycled, fed by a
            # queue): steady-state batch execution spawns no threads.
            self._batch_runners.submit(
                self.pool.run_task_batch, run_tasks, on_result, depth,
                self._pipeline_inflight)

        def reserve_or_park(wants: list, emit_parked) -> list:
            """Batched admission for [(task, demand)]: admitted tasks
            are returned; over-subscribed entries park (the reply loop
            re-admits them as capacity frees); plain rejects spill back
            ("busy",) to the driver exactly as before."""
            accepted = self._try_reserve_many(
                [(t.token, d) for t, d in wants])
            t_admit = time.time()
            admitted = []
            for (task, demand), ok in zip(wants, accepted):
                if ok:
                    admitted.append(task)
                    if task.trace is not None or perf.PERF_ON:
                        admit_ts[task.idx] = t_admit
                elif task.overcommit:
                    parked.append((task, demand))
                    emit_parked(task.idx)
                else:
                    complete(task.idx, ("busy",))
            return admitted

        if reserve_wants:
            pipeline = reserve_or_park(
                reserve_wants,
                lambda idx: _emit_part(("parked", idx)))
        if pipeline:
            launch(pipeline)

        fused_stats = {"fused": 0, "fused_fallbacks": 0}

        def spill_fused(rest: "list[_BatchTask]") -> None:
            # Per-run budget expired mid-fused-run: the remaining
            # fused-eligible entries take the pipelined worker path
            # (admission applies to them like any worker-path entry).
            self.fused_fallbacks += len(rest)
            fused_stats["fused_fallbacks"] += len(rest)
            go = reserve_or_park(
                [(t, demand_by_idx[t.idx]) for t in rest],
                lambda idx: _emit_part(("parked", idx)))
            if go:
                launch(go)

        try:
            done_n = 0
            if fused:
                done_n += self._run_fused(fused, client_addr,
                                          _emit_part, spill_fused,
                                          fused_stats)
            while done_n < n:
                with cond:
                    while not completions and not control:
                        if parked:
                            # Capacity freed by OTHER RPCs' completions
                            # never signals this cond: poll admission
                            # for the parked entries on a short beat.
                            if not cond.wait(timeout=0.05):
                                break
                        else:
                            cond.wait()
                    group, completions = completions, []
                    ctrl, control = control, []
                for kind, idx in ctrl:
                    if idx is not None:
                        _emit_part((kind, idx))
                if group:
                    _emit_part(("results", group))
                    self.reply_groups += 1
                    done_n += len(group)
                    self._notify_load()
                if parked:
                    self._admit_parked(parked, launch, _emit_part,
                                       complete, admit_ts)
        finally:
            if notified_tokens:
                self._pipeline_inflight.forget_notify(notified_tokens)
        return ("done", n, fused_stats)

    # Maybe-started ambiguity window: fused entries are announced to
    # the driver in ("started_many", [idx…]) windows of this many
    # BEFORE any of them can side-effect — one stream part per window
    # instead of one per task. On daemon death, announced-but-
    # never-started entries retry under the system-failure budget
    # (instead of the invisible requeue an unannounced entry gets), so
    # the window bounds how many spurious budget consumptions a death
    # can cost. Results flush in groups of _FUSED_GROUP.
    _FUSED_STARTED_WINDOW = 8
    _FUSED_GROUP = 64
    # Columnar runs announce in wider windows (see _execute_columnar).
    _COL_STARTED_WINDOW = 32

    def _run_fused(self, tasks: list, client_addr: "str | None",
                   emit, spill, fused_stats: dict) -> int:
        """Execute a run of fused entries serially on the calling
        (dispatch) thread, streaming ("started_many", [idx…]) windows
        before their entries can side-effect and grouped
        ("results", ...) parts as they finish. Returns how many entries
        were COMPLETED here; entries past the wall budget are handed to
        ``spill`` (worker path) and complete through the reply loop
        instead.

        Exactly-once accounting leans on stream ordering: a window's
        socket write completes before any of its user functions run,
        and a SIGKILLed daemon's kernel still flushes written stream
        data — so the driver can never invisibly requeue an entry that
        may have executed."""
        from ray_tpu._private.config import GLOBAL_CONFIG

        budget_s = float(GLOBAL_CONFIG.fused_run_wall_budget_s)
        t0 = time.monotonic()
        self.fused_runs += 1
        group: list = []
        done = 0
        announced = 0
        window = self._FUSED_STARTED_WINDOW
        # One resource sample brackets the whole run; per-task wall
        # comes from cheap clock reads and the run's cpu/rss attribute
        # proportionally at the end (per-task getrusage syscalls were
        # a measurable slice of the fused budget).
        perf_on = perf.PERF_ON
        run_sample = perf.sample_start() if perf_on else None
        for pos, task in enumerate(tasks):
            if budget_s > 0 and time.monotonic() - t0 > budget_s:
                if group:
                    emit(("results", group))
                    self.reply_groups += 1
                    done += len(group)
                    group = []
                spill(tasks[pos:])
                break
            if task.deadline is not None and time.time() > task.deadline:
                self.task_timeouts += 1
                group.append((task.idx, ("timeout", "admitted")))
            elif self._cancelled_tokens and \
                    self._token_cancelled(task.token):
                # Speculation first-seal-wins: the sibling copy sealed
                # and this token was loser-cancelled before we ran.
                group.append((task.idx, ("cancelled",)))
            else:
                if pos >= announced:
                    emit(("started_many",
                          [t.idx for t in
                           tasks[announced:announced + window]]))
                    announced += window
                group.append((task.idx,
                              self._exec_fused(task, client_addr)))
                self.fused_tasks += 1
                fused_stats["fused"] += 1
            if len(group) >= self._FUSED_GROUP:
                emit(("results", group))
                self.reply_groups += 1
                done += len(group)
                group = []
        else:
            if group:
                emit(("results", group))
                self.reply_groups += 1
                done += len(group)
        ran = fused_stats["fused"]
        if run_sample is not None and ran:
            # Run-level attribution: exact cpu/wall sums with the
            # task count folded in (per-task getrusage syscalls were a
            # measurable slice of the fused per-task budget). The run
            # is same-signature in the hot path; a mixed run
            # attributes to its first function.
            func = self._func_cache.get(tasks[0].digest)
            name = getattr(func, "__qualname__", tasks[0].digest[:8])
            _, wall, cpu, rss = perf.sample_end(name, run_sample)
            perf.record_task_resources(name, wall, cpu, rss, count=ran)
        self._notify_load()
        return done

    def _exec_fused(self, task, client_addr: "str | None") -> tuple:
        """Run ONE fused entry in-process; returns the execute_task
        reply shape (("ok", descriptors[, trace]) / ("err", blob)).
        No admission reservation, no worker pipe, no per-task pickle of
        the surrounding protocol — the per-task cost is the user
        function plus one args decode and one result encode (both with
        the raw small-immutable fast path)."""
        from ray_tpu._private import worker_client

        try:
            func = self._func_cache.get(task.digest)
            if func is None:
                with self._func_lock:
                    func = self._func_cache.get(task.digest)
                if func is None:
                    func = serialization.loads_function(task.func_blob)
                    with self._func_lock:
                        self._func_cache[task.digest] = func
            args, kwargs = serialization.deserialize_from_buffer(
                memoryview(task.args_blob))
            if client_addr and client_addr != \
                    getattr(self, "_fused_client_addr", None):
                # One env/proxy rebind per owner change, not per task.
                worker_client.set_driver_addr(client_addr)
                self._fused_client_addr = client_addr
            worker_client.set_task_token(task.token)
            perf_on = perf.PERF_ON
            # Cheap per-task exec-stage wall (vDSO clock reads); the
            # cpu/rss attribution samples once per RUN in _run_fused.
            t_exec = time.time() if (perf_on or task.trace is not None) \
                else 0.0
            try:
                result = func(*args, **kwargs)
            finally:
                worker_client.set_task_token(None)
            t_end = time.time() if t_exec else 0.0
            if perf_on and t_exec:
                perf.record_stage("exec", max(0.0, t_end - t_exec))
            n_returns = task.n_returns
            if n_returns == 1:
                values = [result]
            elif n_returns == 0:
                values = []
            else:
                if (not isinstance(result, (tuple, list))
                        or len(result) != n_returns):
                    raise ValueError(
                        f"task declared num_returns={n_returns} but "
                        f"returned {type(result).__name__}")
                values = list(result)
        except BaseException as exc:  # noqa: BLE001 — shipped to driver
            return ("err", _exc_blob(exc))
        out = []
        inline_max = _inline_reply_bytes()
        for id_bytes, value in zip(task.return_keys or (), values):
            try:
                blob = serialization.try_serialize_raw(value)
                if blob is None:
                    blob = serialization.serialize_framed(value)
            except BaseException as exc:  # noqa: BLE001
                out.append(("err", _exc_blob(exc)))
                continue
            if len(blob) <= inline_max:
                out.append(("inline", blob))
            else:
                self.store.put(id_bytes, blob, owner=client_addr)
                self._maybe_export_stored(id_bytes, blob)
                out.append(("stored", len(blob)))
        self.tasks_executed += 1
        if task.trace is not None:
            return ("ok", out, self._batch_trace(
                task, t_exec, {"exec_start": t_exec, "exec_end": t_end,
                               "pid": os.getpid()}))
        return ("ok", out)

    def _execute_columnar(self, descriptor: tuple,
                          client_addr: "str | None",
                          _emit_part) -> tuple:
        """Columnar batch RPC (driver dispatch lanes, ISSUE 15): ONE
        (digest, func_blob, resources) header + parallel
        ``args_blobs`` / ``return_keys`` columns. The whole run is
        fused-eligible by construction (scalar args, no refs, no
        runtime_env, no deadline), so it executes serially on this
        dispatch thread with the per-task cost reduced to one args
        decode + the user function + one result encode — the function
        resolve, client rebind and admission bookkeeping are paid once
        per RUN, not per task.

        Streamed parts: the same ("started_many", [idx…]) exactly-once
        windows as :meth:`_run_fused` (a window's socket write
        completes before any member can side-effect), compact
        ("colresults", (start_idx, [payload…])) groups where a payload
        is the raw inline reply blob (the common case) or a classic
        per-task reply tuple, and — for entries spilled to the worker
        pipeline when the run's wall budget expires — the classic
        ("results", …) / ("parked", …) parts re-indexed into this
        batch. Final reply: ("done", n, fused_stats)."""
        from ray_tpu._private.config import GLOBAL_CONFIG

        (_, digest, func_blob, args_blobs, return_keys, resources,
         token_base) = descriptor
        n = len(args_blobs)
        self.batch_rpcs += 1
        self.batch_tasks_received += n
        fused_stats = {"fused": 0, "fused_fallbacks": 0}
        shed_why = self._overload_reason()
        if shed_why is not None:
            self.admission_shed += n
            _emit_part(("colresults",
                        (0, [("overloaded", shed_why)] * n)))
            return ("done", n, fused_stats)
        if func_blob is not None:
            with self._func_lock:
                self._func_blob_cache[digest] = func_blob
            blob = func_blob
        else:
            with self._func_lock:
                blob = self._func_blob_cache.get(digest)
        if blob is None:
            # Daemon restarted since the driver learned the digest:
            # every entry retries via the single execute path.
            _emit_part(("colresults", (0, [("need_func", None)] * n)))
            return ("done", n, fused_stats)
        fused_cap = (max(1, int(GLOBAL_CONFIG.fused_max_run_tasks))
                     if FUSED_ON else 0)
        budget_s = float(GLOBAL_CONFIG.fused_run_wall_budget_s)
        try:
            func = self._func_cache.get(digest)
            if func is None:
                func = serialization.loads_function(blob)
                with self._func_lock:
                    self._func_cache[digest] = func
        except BaseException as exc:  # noqa: BLE001 — load failure
            err = ("err", _exc_blob(exc))
            _emit_part(("colresults", (0, [err] * n)))
            return ("done", n, fused_stats)
        from ray_tpu._private import worker_client

        if client_addr and client_addr != \
                getattr(self, "_fused_client_addr", None):
            worker_client.set_driver_addr(client_addr)
            self._fused_client_addr = client_addr
        worker_client.set_task_token(token_base)
        # RUN-level admission reservation: one _running entry covers
        # the whole columnar run (shrunk as reply groups flush), so
        # the heartbeat's availability report — and the load-change
        # poke other drivers schedule against — reflects the queued
        # work. Classic per-entry reservations cost a lock pass per
        # task; this is one per run + one per reply group.
        run_token = f"col-{token_base}"
        run_demand = dict(resources or {})
        run_demand.setdefault("CPU", 1.0)

        def _reserve_remaining(remaining: int) -> None:
            with self._running_lock:
                if remaining > 0:
                    self._running[run_token] = {
                        k: v * remaining for k, v in run_demand.items()}
                else:
                    self._running.pop(run_token, None)
            self._notify_load()

        _reserve_remaining(n)
        inline_max = _inline_reply_bytes()
        deser = serialization.deserialize_from_buffer
        ser_raw = serialization.try_serialize_raw
        ser_framed = serialization.serialize_framed
        # Wider exactly-once window than the classic fused run (8):
        # columnar entries are tiny by eligibility, so the daemon-death
        # cost the window bounds (spurious retry-budget consumptions)
        # is cheap, while each announced window is a streamed part —
        # at 32 the announce overhead is a quarter of the classic run.
        window = self._COL_STARTED_WINDOW
        group_max = self._FUSED_GROUP
        perf_on = perf.PERF_ON
        run_sample = perf.sample_start() if perf_on else None
        exec_walls: list = [] if perf_on else None
        t0 = time.monotonic()
        if fused_cap:
            self.fused_runs += 1
        group: list = []
        group_start = 0
        pos = 0
        announced = 0
        try:
            while pos < min(n, fused_cap):
                if budget_s > 0 and time.monotonic() - t0 > budget_s:
                    break  # spill the remainder to the worker path
                if pos >= announced:
                    announced = min(n, pos + window)
                    _emit_part(("started_many",
                                list(range(pos, announced))))
                if self._cancelled_tokens and self._token_cancelled(
                        f"{token_base}:{pos}"):
                    payload = ("cancelled",)
                else:
                    t_exec = time.time() if perf_on else 0.0
                    try:
                        # Columnar blobs encode the args tuple alone
                        # (kwargs empty by eligibility).
                        args = deser(memoryview(args_blobs[pos]))
                        result = func(*args)
                        rblob = ser_raw(result)
                        if rblob is None:
                            rblob = ser_framed(result)
                        if len(rblob) <= inline_max:
                            payload = rblob
                        else:
                            id_bytes = return_keys[pos]
                            self.store.put(id_bytes, rblob,
                                           owner=client_addr)
                            self._maybe_export_stored(id_bytes, rblob)
                            payload = ("ok", [("stored", len(rblob))])
                    except BaseException as exc:  # noqa: BLE001
                        payload = ("err", _exc_blob(exc))
                    if perf_on:
                        exec_walls.append(
                            max(0.0, time.time() - t_exec))
                    self.tasks_executed += 1
                    self.fused_tasks += 1
                    fused_stats["fused"] += 1
                group.append(payload)
                pos += 1
                if len(group) >= group_max:
                    _emit_part(("colresults", (group_start, group)))
                    self.reply_groups += 1
                    group = []
                    group_start = pos
                    _reserve_remaining(n - pos)
        finally:
            worker_client.set_task_token(None)
        if group:
            _emit_part(("colresults", (group_start, group)))
            self.reply_groups += 1
        # Drop the run reservation; a budget-spilled remainder
        # re-reserves per entry through the worker path below.
        _reserve_remaining(0)
        if perf_on and exec_walls:
            perf.record_stage_many("exec", exec_walls)
        if run_sample is not None and fused_stats["fused"]:
            name = getattr(func, "__qualname__", digest[:8])
            _, wall, cpu, rss = perf.sample_end(name, run_sample)
            perf.record_task_resources(name, wall, cpu, rss,
                                       count=fused_stats["fused"])
        self._notify_load()
        if pos < n:
            # Budget spill (or fused disarmed): the remainder rides
            # the classic worker pipeline as over-subscribed no-fuse
            # entries, re-indexed into this batch's idx space.
            rest = list(range(pos, n))
            self.fused_fallbacks += len(rest) if fused_cap else 0
            fused_stats["fused_fallbacks"] += len(rest) \
                if fused_cap else 0
            offset = pos

            def remap(part):
                kind, payload = part
                if kind == "results":
                    _emit_part((kind, [(offset + i, reply)
                                       for i, reply in payload]))
                elif kind == "started_many":
                    _emit_part((kind, [offset + i for i in payload]))
                else:
                    _emit_part((kind, offset + payload))

            entries = []
            for i in rest:
                # Re-frame into the classic (args, kwargs) shape the
                # worker pipe decodes (columnar blobs carry the args
                # tuple alone) — the spill path is rare by design.
                # Flag 8 (no-fuse) WITHOUT the park flag: whatever
                # this node's workers can't admit bounces ("busy",)
                # back to the driver, which SPREADS it across the
                # cluster through the classic dispatcher — a columnar
                # slice that turns out to be long tasks must not
                # serialize a whole run behind one node.
                args = deser(memoryview(args_blobs[i]))
                pair_blob = ser_raw((args, {}))
                if pair_blob is None:
                    pair_blob = ser_framed((args, {}))
                entries.append(
                    (digest, None, pair_blob, 1, [return_keys[i]],
                     None, resources, f"{token_base}:{i}", 8))
            self.execute_task_batch(entries, client_addr,
                                    _emit_part=remap)
        return ("done", n, fused_stats)

    def _admit_parked(self, parked: list, launch, emit, complete,
                      admit_ts: dict) -> None:
        """Daemon-side admission queueing: retry reservation for
        over-subscribed entries parked by this batch RPC. Expired
        budgets seal typed timeouts; newly admitted entries emit
        ("resumed", idx) — the driver re-acquires their CPU — and join
        the worker pipeline as a fresh run."""
        now = time.time()
        still: list = []
        for task, demand in parked:
            if task.deadline is not None and now > task.deadline:
                self.task_timeouts += 1
                complete(task.idx, ("timeout", "admitted"))
            else:
                still.append((task, demand))
        parked[:] = []
        if not still:
            return
        accepted = self._try_reserve_many(
            [(t.token, d) for t, d in still])
        t_admit = time.time()
        go: list = []
        for (task, demand), ok in zip(still, accepted):
            if ok:
                emit(("resumed", task.idx))
                if task.trace is not None or perf.PERF_ON:
                    admit_ts[task.idx] = t_admit
                go.append(task)
            else:
                parked.append((task, demand))
        if go:
            launch(go)

    def fetch_object(self, id_bytes: bytes, offset: int,
                     length: int):
        reply = self.store.read_chunk(id_bytes, offset, length)
        if reply is None:
            # Not (yet) in the store: an in-progress or relay pull may
            # hold the requested chunks — serve them so 1->N broadcast
            # fans out through receivers instead of queueing on the
            # owner.
            with self._partials_lock:
                part = self._partials.get(id_bytes)
            if part is None:
                return None
            reply = part.read_chunk(offset, length)
            if reply is None:
                return None
            self.relay_chunks_served += 1
        return wrap_chunk_reply(reply)

    def fetch_plan(self, id_bytes: bytes,
                   puller_addr: str | None = None,
                   puller_host: str | None = None):
        """Transfer plan for one object: (total_size, other_holders,
        map_source). Registers the puller as a partial holder so later
        pullers fetch chunks from it too. None when the object is
        unknown here.

        ``map_source``: when the puller declared a host identity equal
        to ours and this daemon holds the object in named shared
        memory, the reply carries how to map it directly — kind/name/
        key/size plus a granted lease token that pins the object until
        ``unpin_object`` (or the liveness-gated TTL sweep). Otherwise
        None and the puller takes the chunked path."""
        total = self.store.size(id_bytes)
        if total is None:
            with self._partials_lock:
                part = self._partials.get(id_bytes)
            if part is None:
                with self._shm_args_lock:
                    source = self._map_sources.get(id_bytes)
                if source is None:
                    return None
                total = source[2]
        map_info = None
        if puller_addr and puller_host and puller_host == self.host_id:
            map_info = self._grant_map_lease(id_bytes, puller_addr)
        # A mapping puller never holds servable CHUNKS — registering it
        # as a relay holder would advertise a peer that serves nothing.
        reg_addr = None if map_info is not None else puller_addr
        # Spill-aware reply: a spilled local copy has no shm twin to
        # map (map_info is naturally None — the twin was freed at
        # spill time) and the chunked pull will pay a verify+restore
        # first; the 4th element tells the puller so.
        spilled = bool(getattr(self.store, "is_spilled",
                               lambda _k: False)(id_bytes))
        return (total, plan_holders(self.chunk_directory, id_bytes,
                                    reg_addr, total), map_info,
                {"spilled": spilled})

    def _grant_map_lease(self, id_bytes: bytes,
                         holder: str) -> dict | None:
        """Owner half of the same-host protocol: find a shared-memory
        source for the object and pin it under a lease for ``holder``.
        Segments need no in-memory pin (POSIX keeps a mapped segment
        alive past its unlink), so their lease only tracks the grant;
        arena objects take a real refcount (ArenaStore.pin) that blocks
        eviction/reuse until release."""
        from ray_tpu._private.same_host import map_enabled

        if not map_enabled():
            return None
        with self._shm_args_lock:
            source = self._map_sources.get(id_bytes)
        if source is None:
            return None
        kind, name, size = source[0], source[1], source[2]
        key = source[3] if len(source) > 3 else b""
        if kind == "arena":
            arena = getattr(self, "_owned_arena", None)
            if arena is None or arena.pin(key) is None:
                return None
            token = self.leases.grant(
                id_bytes, holder, on_release=lambda: arena.unpin(key))
        else:
            token = self.leases.grant(id_bytes, holder)
        return {"kind": kind, "name": name, "key": key, "size": size,
                "host": self.host_id, "token": token}

    def unpin_object(self, token: str) -> bool:
        """Release one same-host map lease (puller dropped its
        mapping)."""
        return self.leases.release(token)

    def free_objects(self, ids: list[bytes]) -> int:
        for id_bytes in ids:
            self._drop_shm_arg(id_bytes)
        self.chunk_directory.drop(ids)
        return self.store.free(ids)

    def _drop_shm_arg(self, key: bytes) -> None:
        """Owner GC of one object's transfer-plane state: relay
        partial (buffer view released first — exported-view safety),
        shm segment, and FIFO accounting."""
        with self._partials_lock:
            part = self._partials.pop(key, None)
        if part is not None and part.external:
            with part.lock:
                try:
                    part.buf.release()
                except BufferError:
                    pass
        with self._shm_args_lock:
            self._shm_args_order = [
                (k, sz) for k, sz in self._shm_args_order if k != key]
            self._shm_args_bytes = sum(
                sz for _, sz in self._shm_args_order)
        self._release_plane_state(key)
        self._shm_directory.free(key)

    def _batch_trace(self, task, admitted: float | None,
                     wtrace: dict | None) -> dict:
        """Per-task trace payload for a pipelined batch completion:
        daemon admission stamp + the worker's frame/exec stamps (same
        host, same clock), plus a daemon-lane span and a worker-lane
        span so the merged timeline shows the full hop chain."""
        from ray_tpu.util import tracing

        now = time.time()
        stages: dict = {}
        if admitted is not None:
            stages["admitted"] = admitted
        ctx = task.trace
        if wtrace:
            for key in ("worker_start", "exec_start", "exec_end"):
                if key in wtrace:
                    stages[key] = wtrace[key]
            if "exec_start" in wtrace and "exec_end" in wtrace:
                tracing.buffer_span({
                    "name": "worker:execute",
                    "span_id": os.urandom(8).hex(),
                    "parent_id": ctx[1] if ctx else None,
                    "trace_id": ctx[0] if ctx else "",
                    "start_time": wtrace["exec_start"],
                    "end_time": wtrace["exec_end"],
                    "thread": "task_seq",
                    "proc": f"worker:{wtrace.get('pid', '?')}",
                    "attributes": {"token": task.token or ""},
                })
        if admitted is not None:
            tracing.buffer_span({
                "name": "daemon:task",
                "span_id": os.urandom(8).hex(),
                "parent_id": ctx[1] if ctx else None,
                "trace_id": ctx[0] if ctx else "",
                "start_time": admitted,
                "end_time": now,
                "thread": "batch",
                "proc": _proc_label(),
                "attributes": {"token": task.token or ""},
            })
        return {"stages": stages, "spans": tracing.drain_buffered(),
                "now": now}

    def _pipeline_stats(self) -> dict:
        # Per-stage drain counters for the pipelined execute path
        # (dispatch batches -> batch RPCs -> worker leases/frames ->
        # grouped seal replies) so a throughput regression localizes
        # to one stage in a single read.
        return {
            "batch_rpcs": self.batch_rpcs,
            "batch_tasks": self.batch_tasks_received,
            "reply_groups": self.reply_groups,
            "worker_lease_runs": self.pool.batch_runs,
            "worker_lease_tasks": self.pool.batch_tasks,
            "worker_pipelined_frames": self.pool.batch_frames,
            "fused_runs": self.fused_runs,
            "fused_tasks": self.fused_tasks,
            "fused_fallbacks": self.fused_fallbacks,
            "runner_spawns": self._batch_runners.spawns,
            "runner_reuses": self._batch_runners.reuses,
        }

    def _data_plane_stats(self) -> dict:
        with self._shm_args_lock:
            data_plane = {
                "same_host_map_hits": self.same_host_map_hits,
                "same_host_copy_hits": self.same_host_copy_hits,
                "chunked_pulls": self.chunked_pulls,
                "map_sources": len(self._map_sources),
                "attached_mappings": len(self._attached),
            }
        data_plane["leases"] = self.leases.stats()
        return data_plane

    def _fault_stats(self) -> dict:
        # Failure counters: every recovery path the chaos tests (and
        # the envelope rows) assert — retried idempotent RPCs, batch
        # entries requeued after a worker/daemon death, chunk sources
        # blacklisted mid-pull, orphaned peer mappings swept.
        from ray_tpu._private.rpc import breaker_stats, rpc_retry_count

        return {
            "rpc_retries": rpc_retry_count(),
            "batch_requeues": self.pool.batch_requeues,
            "peer_blacklists": self.peer_blacklists,
            "lease_orphans_swept": self.lease_orphans_swept,
            "arena_orphans_swept": self.arena_orphans_swept,
            "lineage_rebuilds": 0,  # daemons hold no lineage (owners do)
            # Overload-control plane (see FAULT_STAT_KEYS).
            "task_timeouts": self.task_timeouts,
            "admission_shed": self.admission_shed,
            "breaker_open": breaker_stats()["opens"],
        }

    def executor_stats(self) -> dict:
        with self._running_lock:
            running = len(self._running)
        with self._actors_lock:
            num_actors = len(self._actors)
        with self._partials_lock:
            relay = {
                "partials": len(self._partials),
                "relay_chunks_served": self.relay_chunks_served,
            }
        stats = {"tasks_executed": self.tasks_executed,
                 "running": running, "store": self.store.stats(),
                 "num_actors": num_actors, "pid": os.getpid(),
                 "relay": relay,
                 "data_plane": self._data_plane_stats(),
                 "pipeline": self._pipeline_stats(),
                 "faults": self._fault_stats(),
                 "spill": self._spill_stats(),
                 "threads": threading.active_count()}
        engine = self._engine_stats()
        if engine is not None:
            stats["engine"] = engine
        return stats

    def _spill_stats(self) -> dict:
        from ray_tpu._private.spill_manager import merged_stats

        stats = merged_stats(self._spill_mgr)
        stats["spilled_plan_hits"] = self.spilled_plan_hits
        return stats

    @staticmethod
    def _engine_stats() -> "dict | None":
        """LLM-engine counters for engines co-hosted in this process
        (serve replicas run as thread actors here). sys.modules probe:
        a daemon that never served an LLM must not import the serve
        tier just to report stats."""
        import sys

        mod = sys.modules.get("ray_tpu.serve.llm_engine.engine")
        if mod is None:
            return None
        return mod.merged_engine_stats()

    def stats_for_sync(self) -> dict:
        """Heartbeat-piggyback subset of ``executor_stats()``: the
        counter groups the cluster /metrics aggregation serves per node
        (pipeline / data_plane / faults), cheap enough for a 1 s
        cadence — no store-wide byte sums."""
        with self._running_lock:
            running = len(self._running)
            # Admitted-reservation depth net of blocked-in-get tokens:
            # the scheduler's load score wants queue pressure, not
            # parked waiters.
            depth = max(0, running - len(self._blocked_cpu))
        stats = {"tasks_executed": self.tasks_executed,
                 "running": running,
                 "depth": depth,
                 # Snapshot wall stamp: the stats feed carries its own
                 # timestamp so consumers (and the GCS receipt age) can
                 # tell a fresh report from a wedged daemon's last one.
                 "stats_ts": time.time(),
                 "pipeline": self._pipeline_stats(),
                 "data_plane": self._data_plane_stats(),
                 "faults": self._fault_stats()}
        if self._spill_mgr is not None:
            stats["spill"] = self._spill_stats()
            # Spilled/restored location deltas for the GCS object
            # directory (the head pops them before recording stats).
            events = self._drain_spill_events()
            if events:
                stats["spill_events"] = events
        engine = self._engine_stats()
        if engine is not None:
            # LLM-engine counters ride the same heartbeat piggyback
            # into the cluster /metrics (ray_tpu_node_engine family).
            stats["engine"] = engine
        if perf.PERF_ON:
            # Always-on plane piggyback: mergeable-by-addition stage
            # histograms + the per-function attribution table ride the
            # same heartbeat into the GCS node-stats table (the cluster
            # /metrics scrape and summarize_tasks() read them there).
            stats["stage_hist"] = perf.stage_snapshot()
            stats["task_resources"] = perf.resource_snapshot()
        return stats

    def adopt_sys_path(self, paths: list) -> int:
        """Adopt a driver's import paths (existing directories only) so
        functions/classes pickled BY REFERENCE from the driver's modules
        resolve here and in this node's workers. One-machine clusters
        share the filesystem, so the paths are valid; on real multi-host
        the nonexistent ones are skipped and runtime_env py_modules is
        the supported route (reference: the function manager assumes
        importable modules; runtime_env ships the rest)."""
        import sys

        added = 0
        for path in paths:
            if path and path not in sys.path and os.path.isdir(path):
                sys.path.append(path)
                added += 1
        with self._func_lock:
            merged = list(self._driver_sys_path)
            merged += [p for p in paths
                       if p and p not in merged and os.path.isdir(p)]
            self._driver_sys_path = merged
        return added

    def task_block(self, token: str) -> bool:
        """A task on this node blocked in a nested get(): return its CPU
        to the admission ledger so dependent work can land here
        (otherwise a parent waiting on a child scheduled to this node
        deadlocks — reference: blocked workers release their CPU to the
        raylet)."""
        with self._running_lock:
            demand = self._running.get(token)
            if demand is None or token in self._blocked_cpu:
                return False
            cpu = float(demand.get("CPU", 0.0))
            if cpu <= 0:
                return False
            self._blocked_cpu[token] = cpu
            reduced = dict(demand)
            reduced["CPU"] = 0.0
            self._running[token] = reduced
        self._notify_load()
        # Pipelined lease head blocked: frames queued behind it hold
        # CPU without running — park them too (deadlock avoidance).
        self._pipeline_inflight.on_block(token)
        return True

    def task_unblock(self, token: str) -> bool:
        """The blocked task resumed: re-reserve its CPU (may transiently
        overcommit; admission of NEW work still checks the full ledger)."""
        with self._running_lock:
            cpu = self._blocked_cpu.pop(token, None)
            demand = self._running.get(token)
            if cpu is None or demand is None:
                return False
            restored = dict(demand)
            restored["CPU"] = restored.get("CPU", 0.0) + cpu
            self._running[token] = restored
        self._notify_load()
        return True

    # --------------------------------------------------------- actor plane

    def create_actor(self, actor_key: bytes, cls_blob: bytes,
                     args_blob: bytes, runtime_env: dict | None = None,
                     max_concurrency: int = 1,
                     resources: dict | None = None,
                     client_addr: str | None = None,
                     sys_path: list | None = None) -> tuple:
        """Host an actor on this node: admission-reserve its resources
        for its lifetime, spawn a dedicated worker process, run the
        constructor there. -> ("ok", pid) | ("busy",) | ("err", blob).
        (Reference: GcsActorScheduler leases a worker on the chosen node
        and pushes the creation task — gcs_actor_scheduler.h.)"""
        self._warm_factory_once()
        with self._actors_creating_cond:
            self._actors_creating.add(actor_key)
        try:
            return self._create_actor_gated(
                actor_key, cls_blob, args_blob, runtime_env,
                max_concurrency, resources, client_addr, sys_path)
        finally:
            with self._actors_creating_cond:
                self._actors_creating.discard(actor_key)
                self._actors_creating_cond.notify_all()

    def _create_actor_gated(self, actor_key: bytes, cls_blob: bytes,
                            args_blob: bytes,
                            runtime_env: dict | None = None,
                            max_concurrency: int = 1,
                            resources: dict | None = None,
                            client_addr: str | None = None,
                            sys_path: list | None = None) -> tuple:
        with self._actors_lock:
            existing = self._actors.get(actor_key)
        if existing is not None:
            if existing.alive():
                # Driver retry after a lost reply: already up.
                return ("ok", existing.pid)
            # Dead copy: reap it (wait the process, close the pipe,
            # release its reservation) before re-creating.
            self._reap_actor(actor_key)
        demand = dict(resources or {})  # actors default to 0 CPU
        token = "actor-" + actor_key.hex()
        if not self._try_reserve(token, demand):
            return ("busy",)
        try:
            args, kwargs = serialization.deserialize_from_buffer(
                memoryview(args_blob))
            # Actor workers resolve _ShmRef at actor_new: large init
            # args cross as shm descriptors, not pipe payloads.
            args, kwargs = self._resolve_fetch_args(args, kwargs,
                                                    to_shm=True)
            init_blob = serialization.serialize_framed((args, kwargs))
            extra_env = {}
            if client_addr:
                extra_env["RAY_TPU_DRIVER_CLIENT_ADDR"] = client_addr
            # TPU actors own the accelerator from their process. Whole-
            # chip demands are safe: admission then rejects TPU tasks on
            # this node (the daemon process would contend for the same
            # runtime). Fractional TPU sharing across processes is the
            # user's risk — same caveat as the reference's fractional
            # GPUs (reference: TPU_VISIBLE_CHIPS isolation, tpu.py:30).
            allow_tpu = any(k.startswith("TPU") for k in demand)
            worker = None
            if not allow_tpu:
                worker = self._take_standby(extra_env)
            actor = _DaemonActor(cls_blob, init_blob, runtime_env,
                                 max_concurrency, extra_env, allow_tpu,
                                 sys_path, worker=worker)
        except _ActorNewError as exc:
            with self._running_lock:
                self._running.pop(token, None)
            self._notify_load()
            return ("err", exc.blob)
        except BaseException as exc:  # noqa: BLE001 — shipped to driver
            with self._running_lock:
                self._running.pop(token, None)
            self._notify_load()
            return ("err", _exc_blob(exc))
        actor.owner = client_addr  # owner-death sweep kills orphans
        with self._actors_lock:
            self._actors[actor_key] = actor
        return ("ok", actor.pid)

    def actor_call(self, actor_key: bytes, method: str,
                   args_blob: bytes, n_returns: int,
                   return_keys: list[bytes],
                   awaiting_create: bool = False) -> tuple:
        """Invoke a method on a hosted actor. -> ("ok", descriptors)
        with the execute_task result shape (inline/stored per return),
        ("err", blob) for application errors, ("dead", blob) when the
        actor process died, ("gone",) when this daemon does not host the
        actor (e.g. it restarted).

        ``awaiting_create``: the caller pipelined this call behind an
        in-flight create_actor on the same connection — wait for the
        constructor to land (or fail) instead of bouncing "gone", so
        __init__ and the first method call(s) execute back-to-back with
        no driver round trip between them. Plain calls keep the instant
        "gone" (crash detection must not stall)."""
        from ray_tpu._private.worker_pool import (
            _WorkerUnavailable,
        )
        from ray_tpu.exceptions import WorkerCrashedError

        with self._actors_lock:
            actor = self._actors.get(actor_key)
        if actor is None and awaiting_create:
            actor = self._await_actor(actor_key)
        if actor is None:
            return ("gone",)
        try:
            args, kwargs = serialization.deserialize_from_buffer(
                memoryview(args_blob))
            args, kwargs = self._resolve_fetch_args(args, kwargs,
                                                    to_shm=True)
            call_blob = serialization.serialize_framed((args, kwargs))
            status, payload = actor.call(method, call_blob,
                                         max(1, n_returns))
        except (WorkerCrashedError, _WorkerUnavailable) as exc:
            self._reap_actor(actor_key)
            return ("dead", _exc_blob(exc))
        except BaseException as exc:  # noqa: BLE001 — shipped to driver
            return ("err", _exc_blob(exc))
        if status == "err":
            return ("err", payload)
        out = []
        for id_bytes, packed in zip(return_keys, payload):
            blob = self._packed_to_blob(id_bytes, packed)
            if blob is None:
                out.append(packed)  # ("err", blob) passthrough
                continue
            if len(blob) <= _inline_reply_bytes():
                out.append(("inline", blob))
            else:
                self.store.put(id_bytes, blob,
                               owner=getattr(actor, "owner", None))
                self._maybe_export_stored(id_bytes, blob)
                out.append(("stored", len(blob)))
        return ("ok", out)

    def _await_actor(self, actor_key: bytes,
                     grace_s: float = 10.0,
                     create_timeout_s: float = 600.0):
        """Gate for pipelined first calls: wait for the key's in-flight
        creation. The short grace also covers the race where the call's
        dispatch thread outran the create frame's (the driver sent
        create first on the same connection, so the key turns
        "creating" within moments)."""
        import time as _time

        grace_deadline = _time.monotonic() + grace_s
        deadline = _time.monotonic() + create_timeout_s
        seen_creating = False
        with self._actors_creating_cond:
            while True:
                actor = self._actors.get(actor_key)
                if actor is not None:
                    return actor
                now = _time.monotonic()
                if actor_key in self._actors_creating:
                    seen_creating = True
                    if now > deadline:
                        return None
                    self._actors_creating_cond.wait(
                        min(1.0, deadline - now))
                else:
                    # Creation finished without hosting the actor
                    # (busy/err): bounce immediately — the driver
                    # resends once its creation settles elsewhere.
                    if seen_creating or now > grace_deadline:
                        return None
                    self._actors_creating_cond.wait(0.05)

    def _warm_factory_once(self) -> None:
        """First-work trigger: warm the fork-server template in the
        background so the spawn that follows pays only the remaining
        boot time (reference: worker_pool.h prestarts workers ahead of
        demand). NOT at daemon start — a 100-daemon single-box cluster
        would stampede 100 factory boots onto the cores before any
        work arrives (nodes that never execute should never fork)."""
        if getattr(self, "_factory_warmed", False) \
                or os.environ.get("RAY_TPU_WORKER_FACTORY_DISABLE"):
            return
        self._factory_warmed = True

        def _warm():
            try:
                from ray_tpu._private.worker_pool import _get_factory

                _get_factory()
            except Exception:  # noqa: BLE001 — spawns fall back
                pass

        threading.Thread(target=_warm, daemon=True,
                         name="factory-prewarm").start()

    def _take_standby(self, extra_env: dict | None):
        """Pop a live prestarted worker for this spawn env (None on
        miss) and kick an async refill either way."""
        key = tuple(sorted((extra_env or {}).items()))
        worker = None
        with self._standby_lock:
            pool = self._standby.get(key, [])
            while pool:
                candidate = pool.pop()
                if candidate.alive():
                    worker = candidate
                    break
                candidate.stop()
        self._refill_standby(key, extra_env)
        return worker

    def _refill_standby(self, key: tuple, extra_env: dict | None) -> None:
        with self._standby_lock:
            if key in self._standby_refilling:
                return
            self._standby_refilling.add(key)

        def refill():
            from ray_tpu._private.worker_pool import PoolWorker

            try:
                while not self._stop_event.is_set():
                    with self._standby_lock:
                        if len(self._standby.get(key, [])) >= \
                                self._standby_target:
                            return
                    try:
                        worker = PoolWorker(-1, extra_env=dict(key),
                                            allow_tpu=False)
                    except Exception:  # noqa: BLE001 — next take forks
                        return
                    with self._standby_lock:
                        if self._stop_event.is_set():
                            worker.stop()
                            return
                        self._standby.setdefault(key, []).append(worker)
            finally:
                with self._standby_lock:
                    self._standby_refilling.discard(key)

        threading.Thread(target=refill, daemon=True,
                         name="actor-standby-refill").start()

    def actor_kill(self, actor_key: bytes) -> bool:
        return self._reap_actor(actor_key)

    def _reap_actor(self, actor_key: bytes) -> bool:
        with self._actors_lock:
            actor = self._actors.pop(actor_key, None)
        with self._running_lock:
            self._running.pop("actor-" + actor_key.hex(), None)
        self._notify_load()
        if actor is None:
            return False
        actor.kill()
        return True

    def _packed_to_blob(self, id_bytes: bytes, packed: tuple):
        """Worker-pipe result descriptor -> framed blob (None for error
        descriptors, which pass through to the driver)."""
        from ray_tpu._private.ids import ObjectID as _OID
        from ray_tpu._private.shm_store import (
            ArenaDescriptor,
            ShmDescriptor,
        )

        kind = packed[0]
        if kind == "inline":
            return packed[1]  # already framed bytes
        if kind == "arena":
            desc = ArenaDescriptor(packed[1], packed[2])
            self._shm_directory.register_arena(_OID(id_bytes), desc)
            value = self._shm_client.get(desc)
            blob = serialization.serialize_framed(value)
            self._shm_directory.free(_OID(id_bytes))
            return blob
        if kind == "shm":
            desc = ShmDescriptor(packed[1], packed[2])
            rid = _OID(id_bytes)
            self._shm_directory.adopt(rid, desc)
            value = self._shm_client.get(desc)
            blob = serialization.serialize_framed(value)
            self._shm_client.close_segment(desc.name)
            self._shm_directory.free(rid)
            return blob
        return None  # ("err", blob)

    def available_resources(self) -> dict[str, float]:
        """Heartbeat piggyback: total minus the demands of running
        tasks (ray_syncer-lite view for dashboards/autoscaler)."""
        avail = dict(self._resources)
        with self._running_lock:
            for demand in self._running.values():
                for key, value in demand.items():
                    avail[key] = avail.get(key, 0.0) - value
        return avail

    # ------------------------------------------------------------- internals

    def _resolve_fetch_args(self, args: tuple, kwargs: dict,
                            to_shm: bool = False):
        """Resolve FetchRef placeholders. ``to_shm=True`` (worker-bound
        paths) maps each pulled framed blob into a shared-memory
        segment ONCE and substitutes an _ShmRef: the worker
        deserializes straight from the mapping — the daemon never pays
        a deserialize + re-serialize + pipe copy of the payload, and
        repeated tasks using the same broadcast arg share one segment
        (reference: plasma is host-shared by design,
        object_manager/plasma/store_runner.h)."""
        from ray_tpu._private.worker_pool import _ShmRef

        def convert(a):
            if not isinstance(a, FetchRef):
                return a
            if to_shm:
                return _ShmRef(self._shm_fetch_blob(a))
            return self._load_object(a)

        return (tuple(convert(a) for a in args),
                {k: convert(v) for k, v in kwargs.items()})

    def _shm_fetch_blob(self, ref: FetchRef):
        """Framed blob of ``ref`` as a shared-memory descriptor
        (single-flight per object; bounded cache, FIFO eviction).
        Remote pulls land straight in the segment; locally-stored
        blobs are copied into one once and reused by every task."""
        key = ref.id_bytes
        with self._shm_args_lock:
            desc = self._shm_directory.lookup(key)
            # Spill protection: this desc is about to ride a worker
            # frame — the spiller must not unlink its segment before
            # the worker attaches.
            self._shm_out_stamp[key] = time.monotonic()
        if desc is not None:
            return desc
        blob = self.store.get(key)
        if blob is not None:
            return self._blob_to_shm(key, blob)
        return self._fetch_remote(ref, to_shm=True)

    def _load_object(self, ref: FetchRef) -> Any:
        blob = self.store.get(ref.id_bytes)
        if blob is None:
            with self._partials_lock:
                part = self._partials.get(ref.id_bytes)
                if part is not None and part.done.is_set() \
                        and part.error is None:
                    try:
                        blob = bytes(part.buf)
                    except ValueError:
                        blob = None  # view released by eviction
        if blob is None:
            # Peer pull (node-to-node; the driver is never in the path).
            blob = self._fetch_remote(ref)
        return serialization.deserialize_from_buffer(memoryview(blob))

    def _fetch_remote(self, ref: FetchRef, to_shm: bool = False):
        """Pull ``ref`` from the cluster: P2P chunked when the object is
        large enough — the owner hands out a plan (size + holders), this
        node registers partial possession and fetches chunks in parallel
        from every node that has them while relaying its own — plain
        pipelined owner pull otherwise.

        Returns the framed bytes, or (``to_shm=True``) a ShmDescriptor
        whose segment the chunks were pulled STRAIGHT into — the
        worker-bound path never materializes an intermediate copy of
        the whole object."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.same_host import map_enabled

        owner = self._peers.get(ref.addr)
        try:
            # fetch_plan is an idempotent read: ride the shared retry
            # policy so one dropped frame doesn't fail a pull whose
            # owner is alive (exhausted retries propagate — the caller
            # owns the lost-node fallback).
            from ray_tpu._private.rpc import call_with_retry

            plan = call_with_retry(
                owner.call, "fetch_plan", ref.id_bytes,
                self.advertised_address,
                self.host_id if map_enabled() else None,
                attempts=2, timeout_s=30.0)
        except RpcMethodError:
            plan = None  # owner predates fetch_plan
        map_info = plan[2] if plan is not None and len(plan) > 2 \
            else None
        if plan is not None and len(plan) > 3 and plan[3] \
                and plan[3].get("spilled"):
            # The holder's copy is on its disk tier: no map lease can
            # exist and the first chunk pays the holder's restore.
            self.spilled_plan_hits += 1
        if map_info is not None:
            # Co-hosted holder: map its shared memory (or memcpy out of
            # it) instead of moving the bytes through the transport.
            result = self._try_same_host(ref, map_info, to_shm)
            if result is not None:
                return result
        chunk = _fetch_chunk_bytes()
        n_chunks = (-(-plan[0] // chunk)
                    if plan is not None and plan[0] else 0)
        if plan is None or \
                n_chunks < int(GLOBAL_CONFIG.broadcast_min_p2p_chunks):
            self.chunked_pulls += 1
            blob = fetch_blob(owner, ref.id_bytes)
            if to_shm:
                return self._blob_to_shm(ref.id_bytes, blob)
            self.store.put(ref.id_bytes, blob, cached=True)
            return blob
        total, holders = plan[0], plan[1]
        # Single-flight per object: concurrent tasks needing the same
        # arg share one pull instead of racing duplicate transfers.
        with self._partials_lock:
            part = self._partials.get(ref.id_bytes)
            leader = part is None or (part.done.is_set()
                                      and part.error is not None)
            if leader:
                seg = None
                if to_shm:
                    from multiprocessing import shared_memory

                    seg = shared_memory.SharedMemory(
                        create=True, size=max(total, 1))
                    part = _PartialBlob(total, chunk,
                                        buf=memoryview(seg.buf))
                else:
                    part = _PartialBlob(total, chunk)
                self._partials[ref.id_bytes] = part
        if not leader:
            part.done.wait()
            if part.error is None:
                if to_shm:
                    return self._blob_to_shm(ref.id_bytes, None,
                                             part=part)
                with part.lock:
                    return bytes(part.buf)
            # Leader failed; retry as a plain owner pull.
            self.chunked_pulls += 1
            blob = fetch_blob(owner, ref.id_bytes)
            if to_shm:
                return self._blob_to_shm(ref.id_bytes, blob)
            self.store.put(ref.id_bytes, blob, cached=True)
            return blob
        self.chunked_pulls += 1
        try:
            self._pull_chunks(ref, part, holders)
        except BaseException as exc:  # noqa: BLE001 — release waiters
            with self._partials_lock:
                if self._partials.get(ref.id_bytes) is part:
                    del self._partials[ref.id_bytes]
            part.fail(exc)
            if seg is not None:
                try:
                    part.buf.release()
                    seg.unlink()
                    seg.close()
                except (OSError, BufferError):
                    pass  # partial already unusable; raising below
            raise
        if to_shm:
            # The segment is the final copy: register it (workers map
            # it) BEFORE waking waiters, then keep the partial as the
            # relay-serving view.
            desc = self._register_shm_arg(ref.id_bytes, seg, total)
            part.finish()
            self._trim_relays()
            return desc
        blob = part.finish()
        self.store.put(ref.id_bytes, blob, cached=True)
        # Keep serving as a relay while peers are mid-pull — unless the
        # store's pull cache retained the blob (then it serves).
        if self.store.size(ref.id_bytes) is not None:
            with self._partials_lock:
                if self._partials.get(ref.id_bytes) is part:
                    del self._partials[ref.id_bytes]
        else:
            self._trim_relays()
        return blob

    def _try_same_host(self, ref: FetchRef, info: dict, to_shm: bool):
        """Consume a granted same-host map lease: attach the holder's
        segment (zero-copy hand-off to workers) or its arena (cross-
        arena descriptor / single memcpy). Returns a descriptor
        (``to_shm``) or the framed bytes, or None to fall back to the
        chunked path — any failure releases the lease first."""
        key = ref.id_bytes
        token = info.get("token")
        owner_addr = ref.addr
        try:
            if info.get("host") != self.host_id or not token:
                if token:
                    self._unpin_at(owner_addr, token)
                return None
            size = int(info.get("size", 0))
            if info.get("kind") == "seg":
                from ray_tpu._private.same_host import attach_segment
                from ray_tpu._private.shm_store import ShmDescriptor

                try:
                    seg = attach_segment(info["name"])
                except (OSError, ValueError):
                    self._unpin_at(owner_addr, token)
                    return None  # holder freed it: chunked decides
                if to_shm:
                    desc = self._register_shm_arg(
                        key, seg, size,
                        desc=ShmDescriptor(info["name"], size),
                        attached=(owner_addr, token))
                    self.same_host_map_hits += 1
                    return desc
                try:
                    blob = bytes(seg.buf[:size])
                finally:
                    try:
                        seg.close()
                    except (BufferError, OSError):
                        pass  # peer may hold exports; tracker reaps
                self._unpin_at(owner_addr, token)
                self.same_host_copy_hits += 1
                self.store.put(key, blob, cached=True)
                return blob
            if info.get("kind") == "arena":
                view = self._peer_arenas.view(info["name"], info["key"])
                if view is None:
                    self._unpin_at(owner_addr, token)
                    return None
                if to_shm:
                    from ray_tpu._private.shm_store import (
                        PeerArenaDescriptor,
                    )

                    desc = self._register_shm_arg(
                        key, None, size,
                        desc=PeerArenaDescriptor(
                            info["name"], info["key"], size),
                        attached=(owner_addr, token))
                    self.same_host_map_hits += 1
                    return desc
                blob = bytes(view[:size])
                self._unpin_at(owner_addr, token)
                self.same_host_copy_hits += 1
                self.store.put(key, blob, cached=True)
                return blob
            self._unpin_at(owner_addr, token)
            return None
        except Exception:  # noqa: BLE001 — any failure: chunked path
            if token:
                self._unpin_at(owner_addr, token)
            return None

    def _blob_to_shm(self, key: bytes, blob: bytes | None, part=None):
        """Assembled-bytes fallback into a shared segment (small
        objects, plain pulls, non-leader waiters)."""
        from multiprocessing import shared_memory

        with self._shm_args_lock:
            existing = self._shm_directory.lookup(key)
        if existing is not None:
            return existing
        if blob is None:
            with part.lock:
                blob = bytes(part.buf)
        seg = shared_memory.SharedMemory(create=True,
                                         size=max(len(blob), 1))
        seg.buf[:len(blob)] = blob
        return self._register_shm_arg(key, seg, len(blob))

    def _register_shm_arg(self, key: bytes, seg, size: int,
                          desc=None, attached: tuple | None = None):
        """Record a worker-mappable descriptor in the node's shm
        directory (FIFO-bounded; loser of a concurrent promote race
        discards its segment).

        Owned segments (``attached is None``) are also advertised as
        same-host map sources. ``attached=(owner_addr, token)`` records
        a PEER-owned mapping instead: never advertised, never
        unlinked, and its lease is unpinned at the owner when the entry
        is dropped."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.shm_store import ShmDescriptor

        if desc is None:
            desc = ShmDescriptor(seg.name, size)
        evict: list = []
        redundant_lease = None
        with self._shm_args_lock:
            existing = self._shm_directory.lookup(key)
            if existing is not None:
                # Concurrent promote won (no partial references OUR
                # segment here — leaders are single-flight): discard,
                # and release a now-redundant lease AFTER the lock
                # (the unpin call may connect a socket).
                if seg is not None:
                    try:
                        if attached is None:
                            seg.unlink()
                        seg.close()
                    except (OSError, BufferError):
                        pass  # peer may hold exports; tracker reaps
                if attached is not None:
                    redundant_lease = attached
            else:
                self._shm_directory.register(
                    key, desc, seg if attached is None else None)
                if attached is not None:
                    self._attached[key] = (attached[0], attached[1], seg)
                elif seg is not None:
                    self._map_sources[key] = ("seg", seg.name, size)
                self._shm_args_order.append((key, size))
                self._shm_args_bytes += size
                limit = int(GLOBAL_CONFIG.node_pull_cache_mb) \
                    * 1024 * 1024
                while self._shm_args_bytes > limit \
                        and len(self._shm_args_order) > 1:
                    old_key, old_size = self._shm_args_order.pop(0)
                    self._shm_args_bytes -= old_size
                    evict.append(old_key)
        if redundant_lease is not None:
            self._unpin_at(redundant_lease[0], redundant_lease[1])
            return existing
        if existing is not None:
            return existing
        for old_key in evict:
            # Relay partials viewing the evicted segment must release
            # their buffer before the unlink (exported-view safety).
            with self._partials_lock:
                old_part = self._partials.pop(old_key, None)
            if old_part is not None and old_part.external:
                with old_part.lock:
                    try:
                        old_part.buf.release()
                    except BufferError:
                        pass
            self._release_plane_state(old_key)
            self._shm_directory.free(old_key)
        return desc

    def _release_plane_state(self, key: bytes) -> None:
        """Same-host plane GC for one object: drop its owner-side map
        source (+ any leases peers hold on it) and, if this daemon
        holds a PEER's mapping for it, close that and unpin at the
        owner."""
        with self._shm_args_lock:
            self._map_sources.pop(key, None)
            attached = self._attached.pop(key, None)
        self.leases.release_object(key)
        if attached is not None:
            owner_addr, token, seg = attached
            if seg is not None:
                try:
                    seg.close()
                except (BufferError, OSError):
                    pass  # peer may hold exports; tracker reaps
            self._unpin_at(owner_addr, token)

    def _unpin_at(self, owner_addr: str, token: str) -> None:
        """Fire-and-forget lease release at the owner (its TTL sweep
        is the backstop when this RPC is lost)."""
        try:
            self._peers.get(owner_addr).call_async("unpin_object", token)
        except Exception:  # noqa: BLE001 — owner gone: nothing to unpin
            pass

    def _pull_chunks(self, ref: FetchRef, part: _PartialBlob,
                     holders: list[str]) -> None:
        """Sliding-window parallel chunk fetch across owner + peers.

        Chunk order is rotated by a stable hash of this node's address,
        so concurrent receivers start in different regions — the owner
        seeds distinct chunks round-robin and receivers exchange the
        rest among themselves. Routing is REGION-AWARE: every receiver
        derives its peers' start offsets from the same hash, so a chunk
        is requested from the peer that began pulling its region
        earliest (highest hit probability); misses re-issue to the
        owner asynchronously — never a window stall.

        Node-death hardening: a peer that DIES mid-chunk (transport
        failure, not a mere chunk miss) is blacklisted for the rest of
        the pull; when the OWNER dies, the pull re-plans against a
        surviving full holder (any daemon answering ``fetch_plan`` for
        the object) and continues from there — a 1->N broadcast
        survives the producer's crash once one receiver finished."""
        import zlib
        from collections import deque

        from ray_tpu._private.config import GLOBAL_CONFIG

        owner_addr = ref.addr
        fanout = max(0, int(GLOBAL_CONFIG.broadcast_chunk_fanout))
        n_chunks = part.n_chunks()
        my_addr = self.advertised_address
        dead: set[str] = set()
        known_holders = [a for a in holders if a and a != my_addr]

        def peer_starts(addrs: list[str]) -> dict[str, int]:
            return {a: zlib.crc32(a.encode()) % n_chunks
                    for a in dict.fromkeys(addrs)
                    if a and a != my_addr and a not in dead}

        starts = peer_starts(holders[:fanout])
        start = zlib.crc32(my_addr.encode()) % n_chunks
        order = list(range(start, n_chunks)) + list(range(start))
        owner = self._peers.get(owner_addr)
        depth = _pipeline_depth()
        pending: deque = deque()
        completed = 0

        def pick_source(idx: int) -> str:
            # The peer whose rotated start is closest BEHIND idx pulled
            # that region first; beyond half a revolution the owner is
            # the better bet (the peer likely hasn't reached it).
            best, bestd = owner_addr, n_chunks // 2
            for src, s in starts.items():
                d = (idx - s) % n_chunks
                if d < bestd:
                    best, bestd = src, d
            return best

        def issue(idx: int, src: str, attempts: int):
            nonlocal owner_addr, owner
            length = min(part.chunk, part.total - idx * part.chunk)
            while True:
                try:
                    slot = self._peers.get(src).call_async(
                        "fetch_object", ref.id_bytes,
                        idx * part.chunk, length)
                except (RpcError, RpcMethodError, OSError):
                    # Connect-time death (the async path surfaces a
                    # dead peer synchronously): same failover as a
                    # failed in-flight chunk.
                    blacklist(src)
                    if src == owner_addr:
                        survivor = replan_owner()
                        if survivor is None:
                            raise KeyError(
                                f"object {ref.id_bytes.hex()}: owner "
                                f"{owner_addr} unreachable and no "
                                f"surviving holder has a full copy")
                        owner_addr = survivor
                        owner = self._peers.get(owner_addr)
                    attempts += 1
                    if attempts > 3:
                        raise KeyError(
                            f"object {ref.id_bytes.hex()} unreachable "
                            f"on every source")
                    src = owner_addr
                    continue
                pending.append((idx, src, slot, attempts))
                return

        def blacklist(src: str) -> None:
            if src not in dead:
                dead.add(src)
                starts.pop(src, None)
                self.peer_blacklists += 1

        def replan_owner() -> str | None:
            # The authoritative owner died mid-pull: any surviving
            # holder with the FULL object (its fetch_plan reports the
            # total) can serve as the new authority for re-issues and
            # holder refreshes. Partial relays stay chunk sources but
            # cannot anchor retries — a miss there must escalate
            # somewhere that provably has the byte range.
            for addr in dict.fromkeys(list(starts) + known_holders):
                if addr in dead or addr == my_addr:
                    continue
                try:
                    plan = self._peers.get(addr).call(
                        "fetch_plan", ref.id_bytes, my_addr,
                        timeout_s=5.0)
                except (RpcError, RpcMethodError, OSError):
                    blacklist(addr)
                    continue
                if plan is not None and plan[0] == part.total \
                        and self._peers.get(addr).call(
                            "fetch_object", ref.id_bytes, 0, 1,
                            timeout_s=5.0) is not None:
                    return addr
            return None

        it = iter(order)
        exhausted = False
        while pending or not exhausted:
            while not exhausted and len(pending) < depth:
                try:
                    idx = next(it)
                except StopIteration:
                    exhausted = True
                    break
                issue(idx, pick_source(idx), 0)
            if not pending:
                continue
            idx, src, slot, attempts = pending.popleft()
            transport_dead = False
            try:
                reply = slot.result()
            except (RpcError, RpcMethodError):
                reply = None
                transport_dead = True
            if reply is None:
                if transport_dead:
                    # The SOURCE died (vs a mere chunk miss: the peer
                    # answered "don't have it" and stays a candidate).
                    blacklist(src)
                    if src == owner_addr:
                        survivor = replan_owner()
                        if survivor is None:
                            raise KeyError(
                                f"object {ref.id_bytes.hex()}: owner "
                                f"{owner_addr} died mid-pull and no "
                                f"surviving holder has a full copy")
                        owner_addr = survivor
                        owner = self._peers.get(owner_addr)
                if attempts >= 3:
                    raise KeyError(
                        f"object {ref.id_bytes.hex()} not present on "
                        f"{owner_addr}")
                # Re-issue to the authoritative owner (possibly just
                # re-planned) WITHOUT blocking the window.
                issue(idx, owner_addr, attempts + 1)
                continue
            _, data = reply
            part.write(idx, data)
            completed += 1
            if completed % 64 == 0:
                # Refresh the holder set: pullers that registered after
                # our plan are fresh relay sources (and this re-leases
                # our own registration with the owner's directory).
                try:
                    plan = owner.call("fetch_plan", ref.id_bytes,
                                      my_addr)
                    if plan is not None:
                        starts = peer_starts(plan[1][:fanout])
                except (RpcError, RpcMethodError):
                    pass

    _RELAY_TTL_S = 180.0

    def _sweep_transfer_plane(self) -> None:
        """Periodic GC for the P2P plane: expired relay copies and
        stale holder registrations."""
        import time as _time

        now = _time.monotonic()
        expired = []
        with self._partials_lock:
            for id_bytes in [
                    i for i, p in self._partials.items()
                    if p.completed_at is not None
                    and now - p.completed_at > self._RELAY_TTL_S]:
                expired.append(self._partials.pop(id_bytes))
        for part in expired:
            if part.external:
                with part.lock:
                    try:
                        part.buf.release()
                    except BufferError:
                        pass
        self.chunk_directory.prune()
        # Same-host pin leases: expire grants that outlived the TTL
        # whose holder stopped answering pings (a SIGKILLed puller must
        # not pin this daemon's memory forever).
        from ray_tpu._private.same_host import pin_ttl_s

        def _probe(addr: str) -> bool:
            probe = RpcClient(addr, timeout_s=2.0, connect_timeout_s=1.0)
            try:
                return probe.call("ping") == "pong"
            finally:
                probe.close()

        self.leases.sweep(pin_ttl_s(), _probe)
        # Puller side: peer-owned mappings whose OWNER died are orphans
        # — the lease backing the pin is gone with the owner, so the
        # attachment is released (segment closed, directory entry
        # dropped; the next consumer re-pulls and falls back to the
        # chunked path / lineage). Two consecutive failed probes
        # required: one transient miss must not drop a live owner's
        # mappings out from under its workers.
        with self._shm_args_lock:
            owners = {addr for addr, _, _ in self._attached.values()}
        for addr in owners:
            alive = False
            try:
                alive = _probe(addr)
            except Exception:  # noqa: BLE001 — unreachable
                alive = False
            if alive:
                self._attached_owner_strikes.pop(addr, None)
                continue
            strikes = self._attached_owner_strikes.get(addr, 0) + 1
            self._attached_owner_strikes[addr] = strikes
            if strikes < 2:
                continue
            self._attached_owner_strikes.pop(addr, None)
            with self._shm_args_lock:
                victims = [k for k, (a, _, _) in self._attached.items()
                           if a == addr]
            for key in victims:
                self._drop_shm_arg(key)
                self.lease_orphans_swept += 1
        # Crashed co-hosted owners' native arena segments have no
        # surviving unlinker; any live daemon reaps them.
        from ray_tpu._private.same_host import sweep_orphan_shm

        self.arena_orphans_swept += sweep_orphan_shm()
        # Same for a SIGKILLed owner's per-pid spill directory: its
        # files back objects whose store died with it — any co-hosted
        # survivor deletes the whole tier (pid-liveness gated).
        from ray_tpu._private import spill_manager as _spill_mod

        if _spill_mod.SPILL_ON:
            swept = _spill_mod.sweep_orphan_spill_dirs()
            if swept and self._spill_mgr is not None:
                with self._spill_mgr._lock:
                    self._spill_mgr.orphan_dirs_swept += swept

    def _trim_relays(self) -> None:
        """Bound completed relay copies by node_relay_cache_mb (oldest
        finished pulls evicted first; in-progress pulls never are)."""
        from ray_tpu._private.config import GLOBAL_CONFIG

        limit = int(GLOBAL_CONFIG.node_relay_cache_mb) * 1024 * 1024
        evicted = []
        with self._partials_lock:
            finished = sorted(
                ((id_bytes, p) for id_bytes, p in self._partials.items()
                 if p.completed_at is not None),
                key=lambda kv: kv[1].completed_at)
            total = sum(p.total for _, p in finished)
            for id_bytes, p in finished:
                if total <= limit:
                    break
                evicted.append(self._partials.pop(id_bytes))
                total -= p.total
        for part in evicted:
            if part.external:
                with part.lock:
                    try:
                        part.buf.release()
                    except BufferError:
                        pass

    def _run(self, func, digest, func_blob, args, kwargs, n_returns,
             runtime_env, resources, task_token=None,
             client_addr=None, trace=None, trace_stages=None) -> list:
        if any(k.startswith("TPU") for k in resources):
            # TPU tasks run in the daemon process: it owns this node's
            # JAX/TPU runtime (pool workers are pinned to CPU). Each
            # runs on its own dispatch thread (mux server), so a long
            # TPU task never blocks the connection loop; concurrency
            # between TPU tasks is bounded by admission (TPU resource
            # units), and JAX dispatch itself is thread-safe — a mutual-
            # exclusion lock here would deadlock nested TPU-task
            # submission (outer holds it while blocked in get()).
            if perf.PERF_ON:
                # In-daemon run: this dispatch thread IS the executor,
                # so thread_time here is the task's real cpu-seconds.
                sample = perf.sample_start()
                result = func(*args, **kwargs)
                perf.record_task_resources(*perf.sample_end(
                    getattr(func, "__qualname__", digest[:8]), sample))
            else:
                result = func(*args, **kwargs)
        else:
            from ray_tpu._private.worker_pool import _RemoteTaskError

            args_blob = serialization.serialize_framed((args, kwargs))
            if func_blob is None:
                func_blob = serialization.dumps_function(func)
            return_ids = [ObjectID() for _ in range(max(1, n_returns))]
            with self._func_lock:
                sys_path = self._driver_sys_path or None
            try:
                pairs = self.pool.run_task_blobs(
                    digest, func_blob, args_blob, n_returns, return_ids,
                    runtime_env=runtime_env, task_token=task_token,
                    client_addr=client_addr, sys_path=sys_path,
                    trace=trace, stages_out=trace_stages)
            except _RemoteTaskError as rte:
                rte.cause.__ray_tpu_remote_tb__ = rte.remote_tb
                raise rte.cause from None
            return [value for _, value in pairs]
        if n_returns == 0:
            return []
        if n_returns == 1:
            return [result]
        if not isinstance(result, (tuple, list)) or len(result) != n_returns:
            raise ValueError(
                f"task declared num_returns={n_returns} but returned "
                f"{type(result).__name__}")
        return list(result)


def _exc_blob(exc: BaseException) -> bytes:
    import traceback

    tb = "".join(traceback.format_exception(type(exc), exc,
                                            exc.__traceback__))
    try:
        return serialization.serialize_framed((exc, tb))
    except Exception:  # noqa: BLE001 — unpicklable exception
        return serialization.serialize_framed(
            (RuntimeError(f"{type(exc).__name__}: {exc}"), tb))


# --------------------------------------------------------------------------
# Driver side
# --------------------------------------------------------------------------


class RemoteNodeHandle:
    """Driver-side handle to one worker-node executor.

    All task/actor traffic multiplexes on ONE socket (``self.pool``):
    N in-flight calls are seq-tagged and interleaved, not N sockets
    (reference: async completion queues, src/ray/rpc/client_call.h)."""

    def __init__(self, node_id, address: str):
        self.node_id = node_id
        self.address = address
        # "pool" kept for call-site compatibility: it is one multiplexed
        # connection that behaves like an unbounded pool.
        self.pool = MuxRpcClient(address)
        # Monotonic→driver-clock offset estimate for THIS node, anchored
        # half-RTT on traced execute replies (util/tracing.ClockSync):
        # merged timelines correct the daemon's stage stamps with it.
        from ray_tpu.util import tracing

        self.clock = tracing.ClockSync()
        # Short-timeout client for watcher-thread control calls: a ping
        # to an unreachable address must fail fast, never stall the
        # watcher behind the pool's task-length timeouts.
        self._control = RpcClient(address, timeout_s=5.0,
                                  connect_timeout_s=2.0)
        self._digest_lock = lock_witness.Lock(
            "node_executor.RemoteNodeHandle.digest")
        self.known_digests: set[str] = set()
        self._sys_path_sent = False

    def ping(self) -> bool:
        try:
            return self._control.call("ping") == "pong"
        except (RpcError, OSError):
            return False

    def ensure_sys_path(self) -> None:
        """One-shot: hand the node this driver's import paths so
        by-reference pickles (module-level functions/classes) resolve
        there (one-machine clusters share the filesystem)."""
        if self._sys_path_sent:
            return
        import sys

        from ray_tpu._private.rpc import RpcMethodError

        try:
            self._control.call("adopt_sys_path",
                               [p for p in sys.path if p])
            self._sys_path_sent = True
        except (RpcError, RpcMethodError, OSError):
            pass  # best-effort; retried on the next execute

    def execute(self, digest: str, func_blob: bytes, args_blob: bytes,
                n_returns: int, return_keys: list[bytes],
                runtime_env: dict | None,
                resources: dict[str, float],
                task_token: str | None = None,
                client_addr: str | None = None,
                trace_ctx: tuple | None = None,
                deadline: float | None = None) -> tuple:
        """Lease + push + reply. Ships the function blob only the first
        time this node sees its digest. Returns ``(results, trace)``
        where ``trace`` is the daemon's piggybacked trace payload
        (stage stamps + spans + wall clock) or None. Raises
        TaskDeadlineExpired / NodeOverloadedError when the daemon
        refused the lease (deadline dead on arrival / admission shed).
        """
        self.ensure_sys_path()
        with self._digest_lock:
            known = digest in self.known_digests
        # Tracing/deadlines ride as RPC kwargs only when armed: the
        # plain wire shape is byte-identical to before.
        extra = {} if trace_ctx is None else {"trace_ctx": trace_ctx}
        if deadline is not None:
            extra["deadline"] = deadline
        # Coalesced: burst submissions to this node share __batch__
        # frames (one syscall/server wakeup per batch); replies are
        # still per-call, so nothing head-of-line blocks.
        reply = self.pool.call(
            "execute_task", digest, None if known else func_blob,
            args_blob, n_returns, return_keys, runtime_env, resources,
            task_token, client_addr, coalesce=True, **extra)
        if reply[0] == "need_func":
            # Node restarted / cache miss despite our bookkeeping: send
            # the function ALONE — the node stashed the args from the
            # first attempt under a nonce, so they are not re-shipped.
            nonce = reply[1] if len(reply) > 1 else None
            reply = self.pool.call(
                "execute_task", digest, func_blob,
                None if nonce else args_blob, n_returns,
                return_keys, runtime_env, resources, task_token,
                client_addr, nonce, **extra)
            if reply[0] == "stale_args":
                # The stash was evicted between the two calls: full resend.
                reply = self.pool.call(
                    "execute_task", digest, func_blob, args_blob,
                    n_returns, return_keys, runtime_env, resources,
                    task_token, client_addr, **extra)
        if reply[0] == "busy":
            raise NodeBusyError(self.address)
        if reply[0] == "overloaded":
            raise NodeOverloadedError(
                reply[1] if len(reply) > 1 else "admission shed")
        if reply[0] == "timeout":
            raise TaskDeadlineExpired(
                reply[1] if len(reply) > 1 else "admitted")
        if reply[0] == "cancelled":
            raise TaskSpeculationCancelled(self.address)
        with self._digest_lock:
            self.known_digests.add(digest)
        if reply[0] == "err":
            exc, tb = serialization.deserialize_from_buffer(
                memoryview(reply[1]))
            exc.__ray_tpu_remote_tb__ = tb
            raise exc
        return reply[1], (reply[2] if len(reply) > 2 else None)

    def execute_batch(self, entries: list, on_results,
                      on_parked=None, on_resumed=None,
                      client_addr: str | None = None,
                      on_started=None, on_col=None) -> int:
        """One execute_task_batch RPC for a run of tasks leased to this
        node. ``on_results(group)`` fires per streamed completion group
        with [(idx, reply), ...] (execute_task reply shape per task);
        parked/resumed control parts report frames stuck behind a
        blocked lease head; ``on_started(idx)`` marks an entry
        MAYBE-STARTED (its frame reached a worker) — the caller's
        node-death accounting splits unstarted entries (requeued
        invisibly) from started ones (retried under the system-failure
        budget). Returns (replies delivered, fused stats from the
        final ("done", n, stats) reply — {} from a pre-fused daemon);
        the caller fails any missing indexes (stream cut mid-batch).
        Raises RpcError/RpcMethodError like ``execute``."""
        self.ensure_sys_path()
        slot = self.pool.call_streaming(
            "execute_task_batch", entries, client_addr)
        delivered = 0
        while True:
            part = slot.next_part()
            if part is None:
                break
            kind, payload = part
            if kind == "results":
                delivered += len(payload)
                on_results(payload)
            elif kind == "colresults" and on_col is not None:
                # Columnar reply group: (start_idx, [payload…]) — raw
                # inline blobs for the happy path, classic reply
                # tuples for everything else.
                delivered += len(payload[1])
                on_col(payload)
            elif kind == "started" and on_started is not None:
                on_started(payload)
            elif kind == "started_many" and on_started is not None:
                # Fused-run ambiguity window: every member is
                # maybe-started from this part on (one part per window
                # instead of one per task).
                for idx in payload:
                    on_started(idx)
            elif kind == "parked" and on_parked is not None:
                on_parked(payload)
            elif kind == "resumed" and on_resumed is not None:
                on_resumed(payload)
        done = slot.result()  # surfaces transport/method failures
        stats = done[2] if isinstance(done, tuple) and len(done) > 2 \
            else {}
        return delivered, stats

    def fetch(self, id_bytes: bytes) -> bytes:
        return fetch_blob(self.pool, id_bytes)

    def free(self, ids: list[bytes]) -> None:
        self._control.call("free_objects", ids)

    def close(self) -> None:
        self._control.close()
        self.pool.close()
