"""Node executor service — the cluster's distributed execution plane.

TPU-native analogue of the raylet's lease-and-dispatch loop plus the
object manager's node-to-node transfer:

- ``NodeExecutorService`` runs inside every worker-node daemon and
  serves ``execute_task`` over RPC (reference: the raylet grants a
  worker lease and the task is pushed to that node's worker pool —
  src/ray/raylet/node_manager.cc:1714 HandleRequestWorkerLease,
  local_task_manager.h:58). CPU tasks run on the node's own
  multiprocess worker pool; TPU tasks run in the daemon process (which
  owns the node's JAX/TPU runtime).
- ``NodeObjectStore`` holds serialized task results and pulled objects;
  peers and the driver read them with chunked ``fetch_object`` RPCs
  (reference: src/ray/object_manager/object_manager.h:106-130 —
  chunked Push/Pull between nodes).
- ``RemoteNodeHandle`` is the driver side: it leases the task to the
  node, ships the function once per node by digest (function-manager
  pattern), passes remote-located args as ``FetchRef`` location hints
  so the consuming node pulls them peer-to-peer — the driver never
  relays the bytes (reference: ownership_based_object_directory.h, the
  owner hands out locations, data flows node-to-node).

Results above the inline threshold stay on the producing node; the
driver's store holds a ``RemoteBlob`` placeholder that materializes by
chunked pull only when the value is actually read locally.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.rpc import RpcClient, RpcError, RpcServer

# Results at or below this ship inline in the execute_task reply;
# larger ones stay in the producing node's store (driver pulls lazily).
INLINE_REPLY_BYTES = 256 * 1024
FETCH_CHUNK_BYTES = 4 * 1024 * 1024


@dataclass
class FetchRef:
    """Arg placeholder: the value lives in a node's object store —
    resolve by local lookup or a chunked pull from ``addr``."""

    id_bytes: bytes
    addr: str


@dataclass
class RemoteBlob:
    """Driver-store placeholder for a result held on a remote node."""

    node_hex: str
    addr: str
    size: int


class NodeBusyError(Exception):
    """The node rejected the lease at admission (another driver's work
    saturates it); the submitter should spill to a different node."""


class NodeObjectStore:
    """Serialized-blob store of a node daemon: task results (until the
    owner frees them) + pulled peer objects (evictable cache)."""

    def __init__(self, cache_limit_bytes: int = 512 * 1024 * 1024):
        self._lock = threading.Lock()
        self._blobs: dict[bytes, bytes] = {}
        self._cached: dict[bytes, None] = {}  # pulled copies, FIFO evict
        self._cache_limit = cache_limit_bytes
        self._cache_bytes = 0
        self.fetches_served = 0

    def put(self, id_bytes: bytes, blob: bytes, cached: bool = False) -> None:
        with self._lock:
            old = self._blobs.get(id_bytes)
            if old is not None and id_bytes in self._cached:
                self._cache_bytes -= len(old)
                del self._cached[id_bytes]
            self._blobs[id_bytes] = blob
            if cached:
                self._cached[id_bytes] = None
                self._cache_bytes += len(blob)
                while self._cache_bytes > self._cache_limit and self._cached:
                    victim = next(iter(self._cached))
                    del self._cached[victim]
                    dropped = self._blobs.pop(victim, None)
                    if dropped is not None:
                        self._cache_bytes -= len(dropped)

    def get(self, id_bytes: bytes) -> bytes | None:
        with self._lock:
            return self._blobs.get(id_bytes)

    def free(self, ids: list[bytes]) -> int:
        with self._lock:
            n = 0
            for id_bytes in ids:
                blob = self._blobs.pop(id_bytes, None)
                if blob is not None:
                    n += 1
                    if id_bytes in self._cached:
                        del self._cached[id_bytes]
                        self._cache_bytes -= len(blob)
            return n

    def read_chunk(self, id_bytes: bytes, offset: int,
                   length: int) -> tuple[int, bytes] | None:
        with self._lock:
            blob = self._blobs.get(id_bytes)
            if blob is None:
                return None
            self.fetches_served += 1
            return len(blob), blob[offset:offset + length]

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_blobs": len(self._blobs),
                "bytes": sum(len(b) for b in self._blobs.values()),
                "fetches_served": self.fetches_served,
            }


class _PeerClients:
    """One pooled RPC client per peer address (daemon-side pulls)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._clients: dict[str, RpcClient] = {}

    def get(self, addr: str) -> RpcClient:
        with self._lock:
            client = self._clients.get(addr)
            if client is None:
                client = RpcClient(addr)
                self._clients[addr] = client
            return client

    def close(self) -> None:
        with self._lock:
            for client in self._clients.values():
                client.close()
            self._clients.clear()


def fetch_blob(client: RpcClient, id_bytes: bytes) -> bytes:
    """Chunked pull of one object (reference: object_manager.h chunked
    Push — here pull-oriented, sized by FETCH_CHUNK_BYTES)."""
    out = bytearray()
    offset = 0
    while True:
        reply = client.call("fetch_object", id_bytes, offset,
                            FETCH_CHUNK_BYTES)
        if reply is None:
            raise KeyError(
                f"object {id_bytes.hex()} not present on {client.address}")
        total, chunk = reply
        out.extend(chunk)
        offset += len(chunk)
        if offset >= total:
            return bytes(out)


class NodeExecutorService:
    """The daemon-side execution plane: worker pool + object store +
    the RPC surface (execute_task / fetch_object / free_objects)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 pool_size: int | None = None,
                 resources: dict[str, float] | None = None):
        from ray_tpu._private.shm_store import ShmClient, ShmDirectory

        self._server = RpcServer(host, port)
        self.store = NodeObjectStore()
        self._peers = _PeerClients()
        self._resources = dict(resources or {})
        self._running_lock = threading.Lock()
        self._running: dict[str, dict[str, float]] = {}
        self._func_cache: dict[str, Callable] = {}
        self._func_lock = threading.Lock()
        self.tasks_executed = 0

        if pool_size is None:
            pool_size = max(1, min(int(self._resources.get(
                "CPU", os.cpu_count() or 1)), 16))
        from ray_tpu._private.worker_pool import WorkerPool

        self._shm_directory = ShmDirectory()
        self._shm_client = ShmClient()
        self.pool = WorkerPool(pool_size, self._shm_directory,
                               self._shm_client)

        s = self._server
        s.register("ping", lambda: "pong")
        s.register("exec_ping", lambda: os.getpid())
        s.register("execute_task", self.execute_task)
        s.register("fetch_object", self.fetch_object)
        s.register("free_objects", self.free_objects)
        s.register("executor_stats", self.executor_stats)

    @property
    def port(self) -> int:
        return self._server.port

    def address_for(self, host: str) -> str:
        return f"{host}:{self._server.port}"

    def start(self) -> "NodeExecutorService":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop()
        self.pool.shutdown()
        self._peers.close()
        self._shm_client.close_all()
        self._shm_directory.shutdown()

    # ------------------------------------------------------------- endpoints

    def execute_task(self, digest: str, func_blob: bytes | None,
                     args_blob: bytes, n_returns: int,
                     return_keys: list[bytes],
                     runtime_env: dict | None = None,
                     resources: dict | None = None) -> tuple:
        """Run one task; reply ("ok", [result descriptors]) where each
        descriptor is ("inline", blob) or ("stored", size), or
        ("need_func",) when the digest is unknown here, or
        ("err", exc_blob)."""
        # Admission: with several drivers sharing this node, each one
        # accounts only its own leases — reject work beyond capacity and
        # let the submitter spill to another node (reference: raylet
        # spillback, cluster_task_manager.h:42 / HandleRequestWorkerLease
        # redirecting the lease).
        # NOTE: the reservation spans the whole execution, including any
        # time the task spends blocked — daemon-side tasks cannot make
        # nested submissions today (no driver endpoint in daemon pools),
        # so blocked-in-get CPU release does not apply here yet.
        demand = dict(resources or {})
        demand.setdefault("CPU", 1.0)
        token = f"exec-{digest[:8]}-{os.urandom(4).hex()}"
        with self._running_lock:
            for key, cap in self._resources.items():
                used = sum(float(d.get(key, 0.0))
                           for d in self._running.values())
                if used + float(demand.get(key, 0.0)) > float(cap) + 1e-9:
                    return ("busy",)
            # Reserve atomically with the check (two concurrent calls
            # must not both pass a half-full node).
            self._running[token] = demand
        try:
            with self._func_lock:
                func = self._func_cache.get(digest)
            if func is None:
                if func_blob is None:
                    return ("need_func",)
                # Deserialize OUTSIDE the lock: loading can import heavy
                # modules and must not stall other tasks' cache lookups.
                try:
                    func = serialization.loads_function(func_blob)
                except BaseException as exc:  # noqa: BLE001
                    return ("err", _exc_blob(exc))
                with self._func_lock:
                    self._func_cache[digest] = func
            args, kwargs = serialization.deserialize_from_buffer(
                memoryview(args_blob))
            args, kwargs = self._resolve_fetch_args(args, kwargs)
            values = self._run(func, digest, func_blob, args, kwargs,
                               n_returns, runtime_env,
                               resources or {})
        except BaseException as exc:  # noqa: BLE001 — shipped to driver
            return ("err", _exc_blob(exc))
        finally:
            with self._running_lock:
                self._running.pop(token, None)
        self.tasks_executed += 1

        out = []
        for id_bytes, value in zip(return_keys, values):
            try:
                blob = serialization.serialize_framed(value)
            except BaseException as exc:  # noqa: BLE001
                out.append(("err", _exc_blob(exc)))
                continue
            if len(blob) <= INLINE_REPLY_BYTES:
                out.append(("inline", blob))
            else:
                self.store.put(id_bytes, blob)
                out.append(("stored", len(blob)))
        return ("ok", out)

    def fetch_object(self, id_bytes: bytes, offset: int,
                     length: int):
        return self.store.read_chunk(id_bytes, offset, length)

    def free_objects(self, ids: list[bytes]) -> int:
        return self.store.free(ids)

    def executor_stats(self) -> dict:
        with self._running_lock:
            running = len(self._running)
        return {"tasks_executed": self.tasks_executed,
                "running": running, "store": self.store.stats(),
                "pid": os.getpid()}

    def available_resources(self) -> dict[str, float]:
        """Heartbeat piggyback: total minus the demands of running
        tasks (ray_syncer-lite view for dashboards/autoscaler)."""
        avail = dict(self._resources)
        with self._running_lock:
            for demand in self._running.values():
                for key, value in demand.items():
                    avail[key] = avail.get(key, 0.0) - value
        return avail

    # ------------------------------------------------------------- internals

    def _resolve_fetch_args(self, args: tuple, kwargs: dict):
        def convert(a):
            if isinstance(a, FetchRef):
                return self._load_object(a)
            return a

        return (tuple(convert(a) for a in args),
                {k: convert(v) for k, v in kwargs.items()})

    def _load_object(self, ref: FetchRef) -> Any:
        blob = self.store.get(ref.id_bytes)
        if blob is None:
            # Peer pull (node-to-node; the driver is never in the path).
            client = self._peers.get(ref.addr)
            blob = fetch_blob(client, ref.id_bytes)
            self.store.put(ref.id_bytes, blob, cached=True)
        return serialization.deserialize_from_buffer(memoryview(blob))

    def _run(self, func, digest, func_blob, args, kwargs, n_returns,
             runtime_env, resources) -> list:
        if any(k.startswith("TPU") for k in resources):
            # TPU tasks run in the daemon process: it owns this node's
            # JAX/TPU runtime (pool workers are pinned to CPU).
            result = func(*args, **kwargs)
        else:
            from ray_tpu._private.worker_pool import _RemoteTaskError

            args_blob = serialization.serialize_framed((args, kwargs))
            if func_blob is None:
                func_blob = serialization.dumps_function(func)
            return_ids = [ObjectID() for _ in range(max(1, n_returns))]
            try:
                pairs = self.pool.run_task_blobs(
                    digest, func_blob, args_blob, n_returns, return_ids,
                    runtime_env=runtime_env)
            except _RemoteTaskError as rte:
                rte.cause.__ray_tpu_remote_tb__ = rte.remote_tb
                raise rte.cause from None
            return [value for _, value in pairs]
        if n_returns == 0:
            return []
        if n_returns == 1:
            return [result]
        if not isinstance(result, (tuple, list)) or len(result) != n_returns:
            raise ValueError(
                f"task declared num_returns={n_returns} but returned "
                f"{type(result).__name__}")
        return list(result)


def _exc_blob(exc: BaseException) -> bytes:
    import traceback

    tb = "".join(traceback.format_exception(type(exc), exc,
                                            exc.__traceback__))
    try:
        return serialization.serialize_framed((exc, tb))
    except Exception:  # noqa: BLE001 — unpicklable exception
        return serialization.serialize_framed(
            (RuntimeError(f"{type(exc).__name__}: {exc}"), tb))


# --------------------------------------------------------------------------
# Driver side
# --------------------------------------------------------------------------


class _RpcClientPool:
    """Connection pool to one node: execute_task blocks for the task's
    duration, so concurrent in-flight tasks need parallel sockets (the
    single-socket RpcClient would head-of-line block them)."""

    def __init__(self, address: str, timeout_s: float = 24 * 3600.0):
        self.address = address
        self._timeout = timeout_s
        self._lock = threading.Lock()
        self._idle: list[RpcClient] = []

    def acquire(self) -> RpcClient:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return RpcClient(self.address, timeout_s=self._timeout)

    def release(self, client: RpcClient) -> None:
        with self._lock:
            if len(self._idle) < 16:
                self._idle.append(client)
                return
        client.close()

    def call(self, method: str, *args) -> Any:
        client = self.acquire()
        try:
            result = client.call(method, *args)
        except BaseException:
            client.close()
            raise
        self.release(client)
        return result

    def close(self) -> None:
        with self._lock:
            for client in self._idle:
                client.close()
            self._idle.clear()


class RemoteNodeHandle:
    """Driver-side handle to one worker-node executor."""

    def __init__(self, node_id, address: str):
        self.node_id = node_id
        self.address = address
        self.pool = _RpcClientPool(address)
        # Short-timeout client for watcher-thread control calls: a ping
        # to an unreachable address must fail fast, never stall the
        # watcher behind the pool's task-length timeouts.
        self._control = RpcClient(address, timeout_s=5.0,
                                  connect_timeout_s=2.0)
        self._digest_lock = threading.Lock()
        self.known_digests: set[str] = set()

    def ping(self) -> bool:
        try:
            return self._control.call("ping") == "pong"
        except (RpcError, OSError):
            return False

    def execute(self, digest: str, func_blob: bytes, args_blob: bytes,
                n_returns: int, return_keys: list[bytes],
                runtime_env: dict | None,
                resources: dict[str, float]) -> list:
        """Lease + push + reply. Ships the function blob only the first
        time this node sees its digest."""
        with self._digest_lock:
            known = digest in self.known_digests
        reply = self.pool.call(
            "execute_task", digest, None if known else func_blob,
            args_blob, n_returns, return_keys, runtime_env, resources)
        if reply[0] == "need_func":
            # Node restarted / cache miss despite our bookkeeping.
            reply = self.pool.call(
                "execute_task", digest, func_blob, args_blob, n_returns,
                return_keys, runtime_env, resources)
        if reply[0] == "busy":
            raise NodeBusyError(self.address)
        with self._digest_lock:
            self.known_digests.add(digest)
        if reply[0] == "err":
            exc, tb = serialization.deserialize_from_buffer(
                memoryview(reply[1]))
            exc.__ray_tpu_remote_tb__ = tb
            raise exc
        return reply[1]

    def fetch(self, id_bytes: bytes) -> bytes:
        client = self.pool.acquire()
        try:
            blob = fetch_blob(client, id_bytes)
        except BaseException:
            client.close()
            raise
        self.pool.release(client)
        return blob

    def free(self, ids: list[bytes]) -> None:
        self._control.call("free_objects", ids)

    def close(self) -> None:
        self._control.close()
        self.pool.close()
