"""Cluster history plane — head-side time-series ring store + SLO
health watchdog.

Every observability surface before this one (stage-latency histograms,
fault/spill/engine counters, /metrics) is cumulative-since-boot or
instantaneous. This module turns the per-node cumulative stats already
piggybacked on heartbeats (node_executor.stats_for_sync → gcs
node-stats table) into bounded per-interval history:

- ``HistoryStore``: a fixed-interval ring buffer per node, sharded
  along the PR 16 node-stats domains (``gcs_shard.shard_of(node_hex)``)
  with cross-domain merge at query time. Each interval the head's
  monitor tick delta-encodes the cumulative counters into per-interval
  samples (``HISTORY_STAT_KEYS`` rows plus stage-latency histogram
  bucket deltas); a counter that went BACKWARD (daemon restart reset
  it) clamps to zero and rebaselines instead of emitting a negative
  rate. Retention is bounded (``metrics_history_retention_s``); when a
  GCS shard domain stalls, its nodes' samples are stale-marked and
  queries report the domain in ``degraded`` instead of blocking.
- shared windowed-latency helpers (``snapshot_delta``/``summarize``):
  the bucket-subtraction trick PR 14's serve router hand-rolled for
  its controller push, generalized here as THE one implementation
  (serve/router.py now imports it).
- ``HealthWatchdog``: a rule sweep each interval emitting typed
  verdicts (``HEALTH_RULES``) — overload (sustained admission sheds),
  breaker_storm, spill_thrash, stale_shard / wedged_node (age_s past
  bound), fused_fallback_spike. A verdict becoming active is
  flight-recorded (``health.<rule>``), exported as
  ``ray_tpu_health{rule=,node=}`` and served over the
  ``cluster_health`` RPC with the evidence window behind it.

Reference: the Ray paper's GCS-centric control plane treats aggregated
cluster state as the substrate for scheduling/autoscaling decisions
(arxiv 1712.05889 §4.2); this is the windowed feed ROADMAP items 5/6
consume. Disarmed (``metrics_history=0``), the head's monitor tick
pays one module-attribute branch (``HISTORY_ON``).
"""

from __future__ import annotations

import time
from collections import deque

from ray_tpu._private import gcs_shard, lock_witness, perf_plane

# The ONE disarm branch (same discipline as perf_plane.PERF_ON).
HISTORY_ON = True


def init_from_config() -> None:
    """Arm/disarm the history plane from config (head boot reaches
    this through import; RAY_TPU_METRICS_HISTORY=0 disarms)."""
    global HISTORY_ON
    from ray_tpu._private.config import GLOBAL_CONFIG

    HISTORY_ON = bool(GLOBAL_CONFIG.metrics_history)


try:
    init_from_config()
except Exception:  # noqa: BLE001 — config unavailable mid-bootstrap
    pass


# Canonical per-interval sample row, exported so the README doc-drift
# check and the analysis counter-keys pass can assert every key without
# standing up a head. Counters are PER-INTERVAL DELTAS of the
# heartbeat-shipped cumulative stats; gauge keys are point samples.
HISTORY_STAT_KEYS = (
    "tasks_executed", "admission_shed", "breaker_open", "task_timeouts",
    "rpc_retries", "spills", "restores", "restore_p50_ms",
    "fused_fallbacks", "chunked_pulls", "same_host_map_hits",
    "prefill_tokens", "decode_tokens", "running", "depth",
)
# Point-sample keys (everything else in the registry delta-encodes).
GAUGE_KEYS = frozenset({"restore_p50_ms", "running", "depth"})
# The delta-encoded (rate-derivable) subset, precomputed for query().
_COUNTER_KEYS = tuple(k for k in HISTORY_STAT_KEYS
                      if k not in GAUGE_KEYS)
# Where each registry key lives in a stats_for_sync() row:
# (group or None for top-level, field).
_STAT_SOURCES = {
    "tasks_executed": (None, "tasks_executed"),
    "admission_shed": ("faults", "admission_shed"),
    "breaker_open": ("faults", "breaker_open"),
    "task_timeouts": ("faults", "task_timeouts"),
    "rpc_retries": ("faults", "rpc_retries"),
    "spills": ("spill", "spills"),
    "restores": ("spill", "restores"),
    "restore_p50_ms": ("spill", "restore_p50_ms"),
    "fused_fallbacks": ("pipeline", "fused_fallbacks"),
    "chunked_pulls": ("data_plane", "chunked_pulls"),
    "same_host_map_hits": ("data_plane", "same_host_map_hits"),
    "prefill_tokens": ("engine", "prefill_tokens"),
    "decode_tokens": ("engine", "decode_tokens"),
    "running": (None, "running"),
    "depth": (None, "depth"),
}

# Typed watchdog verdicts — THE rule registry (the README rule table
# and tests/test_doc_drift.py assert against this tuple; the verdict
# flight-recorder kind is ``health.<rule>``).
HEALTH_RULES = (
    "overload", "breaker_storm", "spill_thrash",
    "stale_shard", "wedged_node", "fused_fallback_spike",
)


# -- shared windowed-latency helpers ----------------------------------
def counter_delta(cur: float, prev: float) -> float:
    """``max(0, cur - prev)``: a restarted daemon resets its cumulative
    counters mid-series; the clamp rebaselines instead of emitting a
    negative rate."""
    delta = float(cur) - float(prev)
    return delta if delta > 0.0 else 0.0


def snapshot_delta(cur: dict, prev: dict | None) -> dict:
    """Bucket-subtraction window over two cumulative histogram
    snapshots (perf_plane shape: counts/sum/count): the per-window
    histogram is the elementwise difference, clamped at zero so a
    counter reset cannot produce a negative bucket. ``prev=None``
    returns ``cur`` itself (the first window since boot)."""
    counts = [int(c) for c in (cur.get("counts") or [])]
    if not prev:
        return {"counts": counts, "sum": float(cur.get("sum", 0.0)),
                "count": int(cur.get("count", 0))}
    prev_counts = list(prev.get("counts") or [])
    n = max(len(counts), len(prev_counts))
    delta_counts = [
        max(0, (int(counts[i]) if i < len(counts) else 0)
            - (int(prev_counts[i]) if i < len(prev_counts) else 0))
        for i in range(n)]
    count = max(0, int(cur.get("count", 0)) - int(prev.get("count", 0)))
    delta_sum = float(cur.get("sum", 0.0)) - float(prev.get("sum", 0.0))
    if count == 0 or delta_sum < 0.0:
        delta_sum = 0.0
    return {"counts": delta_counts, "sum": delta_sum, "count": count}


def summarize(snap: dict) -> dict:
    """count / mean / p50 / p99 of one histogram snapshot — the shape
    the serve autoscaler feed and the history queries both serve."""
    count = int(snap.get("count", 0))
    return {
        "count": count,
        "mean_s": (float(snap.get("sum", 0.0)) / count) if count
        else 0.0,
        "p50_s": perf_plane.quantile(snap, 0.5),
        "p99_s": perf_plane.quantile(snap, 0.99),
    }


def merge_window(samples: list, stage: str) -> dict:
    """Merge one stage's per-interval histogram deltas back into one
    window snapshot (exact bucket addition — deltas are mergeable the
    same way cumulative snapshots are)."""
    # Seeded empty: merge_snapshots initializes the bucket vector on
    # first fold (a pre-seeded [] would pin the length at zero).
    merged: dict = {}
    for sample in samples:
        snap = (sample.get("stage_hist") or {}).get(stage)
        if isinstance(snap, dict):
            perf_plane.merge_snapshots(merged, snap)
    return merged


def _encode_sample(stats: dict, prev: dict) -> dict:
    """Delta-encode one node's cumulative heartbeat stats row into one
    per-interval sample (exactly the HISTORY_STAT_KEYS row). ``prev``
    is the node's last-seen cumulative value per counter key, updated
    in place; a key's first sighting contributes a zero delta (the
    cumulative-since-boot total is not an interval rate)."""
    sample = {key: 0.0 for key in HISTORY_STAT_KEYS}
    for key in HISTORY_STAT_KEYS:
        group, field = _STAT_SOURCES[key]
        row = stats if group is None else (stats.get(group) or {})
        if not isinstance(row, dict):
            row = {}
        try:
            value = float(row.get(field, 0.0) or 0.0)
        except (TypeError, ValueError):
            value = 0.0
        if key in GAUGE_KEYS:
            sample[key] = value
        else:
            sample[key] = counter_delta(value, prev.get(key, value))
            prev[key] = value
    return sample


def rate_over_window(samples: list, key: str,
                     interval_s: float) -> float:
    """Per-second rate of one delta-encoded counter over a sample
    window (covered time = samples x interval, so a short history
    right after boot is not diluted by the empty remainder)."""
    if not samples:
        return 0.0
    total = sum(float(s.get(key, 0.0) or 0.0) for s in samples)
    return total / max(len(samples) * max(interval_s, 1e-9), 1e-9)


class _NodeSeries:
    """One node's bounded sample ring + its delta-encoder state."""

    __slots__ = ("samples", "prev", "prev_hist", "last_seen")

    def __init__(self, capacity: int):
        self.samples: deque = deque(maxlen=capacity)
        self.prev: dict = {}
        self.prev_hist: dict = {}
        self.last_seen = 0.0


class _Domain:
    """One shard domain of the store: its own lock + node series table
    (mirrors the PR 16 NodeStatsShard split so a wedged domain marks
    exactly the nodes whose control-plane shard wedged)."""

    __slots__ = ("index", "lock", "series")

    def __init__(self, index: int):
        self.index = index
        self.lock = lock_witness.Lock("metrics_history.HistoryStore")
        self.series: dict[str, _NodeSeries] = {}


class HistoryStore:
    """Fixed-interval ring-buffer time-series store over the GCS
    node-stats table. The head's monitor tick drives ``sample()``;
    ``query()`` merges across shard domains and stale-marks the ones
    whose control-plane shard is stalled."""

    def __init__(self, interval_s: float, retention_s: float,
                 domains: int = 1, clock=time.monotonic,
                 wall=time.time):
        self.interval_s = max(0.1, float(interval_s))
        self.retention_s = max(self.interval_s, float(retention_s))
        self.capacity = max(2, int(self.retention_s / self.interval_s))
        self._clock = clock
        self._wall = wall
        self._domains = [_Domain(i) for i in range(max(1, int(domains)))]
        self._last_sample = 0.0
        self._stalled: tuple = ()
        self.samples_taken = 0

    @classmethod
    def from_config(cls, domains: int = 1) -> "HistoryStore":
        from ray_tpu._private.config import GLOBAL_CONFIG

        return cls(
            float(GLOBAL_CONFIG.metrics_history_interval_s),
            float(GLOBAL_CONFIG.metrics_history_retention_s),
            domains=domains)

    def domain_of(self, node_hex: str) -> int:
        return gcs_shard.shard_of(node_hex, len(self._domains))

    def due(self, now: float | None = None) -> bool:
        now = self._clock() if now is None else now
        return now - self._last_sample >= self.interval_s

    def sample(self, node_stats: dict,
               shard_rows: list | None = None) -> int:
        """Record one interval: delta-encode every node's cumulative
        row into its domain's ring. Domains whose GCS shard is
        currently stalled (a nonzero age_s on its shard_stats row)
        record stale-marked samples. Returns nodes sampled."""
        now = self._clock()
        ts = self._wall()
        stalled = tuple(sorted(
            int(row.get("shard", 0)) for row in (shard_rows or [])
            if float(row.get("age_s", 0.0) or 0.0) > 0.0))
        self._stalled = stalled
        self._last_sample = now
        self.samples_taken += 1
        recorded = 0
        n_domains = len(self._domains)
        for node_hex, stats in (node_stats or {}).items():
            if not isinstance(stats, dict):
                continue
            domain = self._domains[
                gcs_shard.shard_of(node_hex, n_domains)]
            stale = domain.index in stalled
            with domain.lock:
                series = domain.series.get(node_hex)
                if series is None:
                    series = _NodeSeries(self.capacity)
                    domain.series[node_hex] = series
                sample = _encode_sample(stats, series.prev)
                sample["ts"] = ts
                sample["age_s"] = float(stats.get("age_s", 0.0) or 0.0)
                sample["stale"] = stale
                hists = stats.get("stage_hist")
                if isinstance(hists, dict):
                    deltas = {}
                    for stage, snap in hists.items():
                        if not isinstance(snap, dict):
                            continue
                        delta = snapshot_delta(
                            snap, series.prev_hist.get(stage))
                        series.prev_hist[stage] = {
                            "counts": list(snap.get("counts") or []),
                            "sum": float(snap.get("sum", 0.0)),
                            "count": int(snap.get("count", 0))}
                        if delta["count"]:
                            deltas[stage] = delta
                    if deltas:
                        sample["stage_hist"] = deltas
                series.samples.append(sample)
                series.last_seen = now
                recorded += 1
        self._prune(now)
        return recorded

    def _prune(self, now: float) -> None:
        """Drop series for nodes gone longer than the retention window
        (dead/churned nodes must not pin their rings forever)."""
        for domain in self._domains:
            with domain.lock:
                for node_hex in list(domain.series):
                    series = domain.series[node_hex]
                    if now - series.last_seen > self.retention_s:
                        del domain.series[node_hex]

    def degraded(self) -> list:
        """Shard domains currently serving stale-marked samples."""
        return list(self._stalled)

    def query(self, window_s: float | None = None,
              node: str | None = None) -> dict:
        """Windowed cross-domain merge: per node, the samples inside
        the window plus per-key rate-over-window for every counter in
        the registry. ``node`` filters by hex prefix. Samples out of a
        stalled domain carry ``stale``; the stalled domains themselves
        ride ``degraded``."""
        ts = self._wall()
        window = float(window_s) if window_s else self.retention_s
        nodes: dict = {}
        for domain in self._domains:
            with domain.lock:
                for node_hex, series in domain.series.items():
                    if node and not node_hex.startswith(node):
                        continue
                    samples = [dict(s) for s in series.samples
                               if ts - float(s.get("ts", 0.0))
                               <= window + self.interval_s / 2.0]
                    if not samples:
                        continue
                    rates = {
                        key: round(rate_over_window(
                            samples, key, self.interval_s), 6)
                        for key in _COUNTER_KEYS}
                    nodes[node_hex] = {
                        "samples": samples,
                        "rates": rates,
                        "stale": any(s.get("stale") for s in samples),
                        "domain": domain.index,
                    }
        return {"armed": True, "interval_s": self.interval_s,
                "retention_s": self.retention_s, "window_s": window,
                "ts": ts, "degraded": self.degraded(), "nodes": nodes}


# -- health watchdog --------------------------------------------------
def _thresholds_from_config() -> dict:
    from ray_tpu._private.config import GLOBAL_CONFIG

    return {
        "window_s": float(GLOBAL_CONFIG.health_window_s),
        "overload_shed_per_s": float(
            GLOBAL_CONFIG.health_overload_shed_per_s),
        "breaker_storm_opens": float(
            GLOBAL_CONFIG.health_breaker_storm_opens),
        "spill_churn_per_s": float(
            GLOBAL_CONFIG.health_spill_churn_per_s),
        "spill_restore_p50_ms": float(
            GLOBAL_CONFIG.health_spill_restore_p50_ms),
        "wedged_age_s": float(GLOBAL_CONFIG.health_wedged_age_s),
        "stale_shard_age_s": float(
            GLOBAL_CONFIG.health_stale_shard_age_s),
        "fused_fallback_per_s": float(
            GLOBAL_CONFIG.health_fused_fallback_per_s),
    }


def _verdict(rule: str, node: str, value: float, threshold: float,
             window_s: float, ts: float, detail: str,
             evidence: dict) -> dict:
    return {"rule": rule, "node": node, "value": round(value, 4),
            "threshold": threshold, "window_s": window_s, "ts": ts,
            "detail": detail, "evidence": evidence}


def _node_windows(hist: dict):
    for node_hex, row in sorted((hist.get("nodes") or {}).items()):
        yield node_hex, row, row.get("samples") or []


def _rule_overload(thresholds: dict, hist: dict, node_stats: dict,
                   shard_rows: list, ts: float) -> list:
    """Sustained admission sheds: the shed rate over the window is
    past bound AND at least two intervals shed (one burst is
    backpressure; sustained shedding is an overloaded node)."""
    thr = thresholds["overload_shed_per_s"]
    window = thresholds["window_s"]
    out = []
    for node_hex, row, samples in _node_windows(hist):
        sheds = [float(s.get("admission_shed", 0.0)) for s in samples]
        rate = row["rates"].get("admission_shed", 0.0)
        nonzero = sum(1 for shed in sheds if shed > 0.0)
        if nonzero >= 2 and rate >= thr:
            out.append(_verdict(
                "overload", node_hex, rate, thr, window, ts,
                f"admission shedding {rate:.2f}/s sustained over "
                f"{nonzero} intervals",
                {"admission_shed": sheds[-10:],
                 "intervals_shedding": nonzero}))
    return out


def _rule_breaker_storm(thresholds: dict, hist: dict, node_stats: dict,
                        shard_rows: list, ts: float) -> list:
    """Circuit-breaker opens piling up inside one window: a sick
    destination is eating whole retry budgets cluster-wide."""
    thr = thresholds["breaker_storm_opens"]
    window = thresholds["window_s"]
    out = []
    for node_hex, row, samples in _node_windows(hist):
        opens = [float(s.get("breaker_open", 0.0)) for s in samples]
        total = sum(opens)
        if total >= thr:
            out.append(_verdict(
                "breaker_storm", node_hex, total, thr, window, ts,
                f"{total:.0f} breaker opens in {window:.0f}s",
                {"breaker_open": opens[-10:]}))
    return out


def _rule_spill_thrash(thresholds: dict, hist: dict, node_stats: dict,
                       shard_rows: list, ts: float) -> list:
    """Spill/restore churn past bound while restores are slow: the
    working set is cycling through disk instead of fitting memory."""
    thr = thresholds["spill_churn_per_s"]
    p50_thr = thresholds["spill_restore_p50_ms"]
    window = thresholds["window_s"]
    out = []
    for node_hex, row, samples in _node_windows(hist):
        churn = row["rates"].get("spills", 0.0) \
            + row["rates"].get("restores", 0.0)
        p50_ms = float(samples[-1].get("restore_p50_ms", 0.0)) \
            if samples else 0.0
        if churn >= thr and p50_ms >= p50_thr:
            out.append(_verdict(
                "spill_thrash", node_hex, churn, thr, window, ts,
                f"spill/restore churn {churn:.2f}/s with restore "
                f"p50 {p50_ms:.1f}ms",
                {"spills_per_s": row["rates"].get("spills", 0.0),
                 "restores_per_s": row["rates"].get("restores", 0.0),
                 "restore_p50_ms": p50_ms}))
    return out


def _rule_stale_shard(thresholds: dict, hist: dict, node_stats: dict,
                      shard_rows: list, ts: float) -> list:
    """A GCS shard domain stalled past bound: its reads serve a stale
    view, its writes queue — history for its nodes is degraded."""
    thr = thresholds["stale_shard_age_s"]
    window = thresholds["window_s"]
    out = []
    for row in shard_rows or []:
        age = float(row.get("age_s", 0.0) or 0.0)
        if age >= thr:
            index = int(row.get("shard", 0))
            out.append(_verdict(
                "stale_shard", f"shard:{index}", age, thr, window, ts,
                f"gcs shard {index} stalled {age:.1f}s "
                f"(queued_writes={row.get('queued_writes', 0)})",
                {"shard": index, "age_s": age,
                 "queued_writes": row.get("queued_writes", 0),
                 "shed_writes": row.get("shed_writes", 0)}))
    return out


def _rule_wedged_node(thresholds: dict, hist: dict, node_stats: dict,
                      shard_rows: list, ts: float) -> list:
    """A node's stats receipt age past bound: the daemon stopped
    heartbeating (wedged or partitioned) but is not yet declared
    dead — its load view and history are both suspect."""
    thr = thresholds["wedged_age_s"]
    window = thresholds["window_s"]
    out = []
    for node_hex, stats in sorted((node_stats or {}).items()):
        if not isinstance(stats, dict):
            continue
        age = float(stats.get("age_s", 0.0) or 0.0)
        if age >= thr:
            out.append(_verdict(
                "wedged_node", node_hex, age, thr, window, ts,
                f"no stats heartbeat for {age:.1f}s",
                {"age_s": age,
                 "running": stats.get("running", 0)}))
    return out


def _rule_fused_fallback_spike(thresholds: dict, hist: dict,
                               node_stats: dict, shard_rows: list,
                               ts: float) -> list:
    """Fused-eligible entries spilling to the worker pipeline at rate:
    the per-run wall budget is blowing — fused runs carry tasks too
    long for the dispatch thread."""
    thr = thresholds["fused_fallback_per_s"]
    window = thresholds["window_s"]
    out = []
    for node_hex, row, samples in _node_windows(hist):
        rate = row["rates"].get("fused_fallbacks", 0.0)
        if rate >= thr:
            out.append(_verdict(
                "fused_fallback_spike", node_hex, rate, thr, window,
                ts, f"fused fallbacks {rate:.2f}/s",
                {"fused_fallbacks": [
                    float(s.get("fused_fallbacks", 0.0))
                    for s in samples[-10:]]}))
    return out


_RULES = {
    "overload": _rule_overload,
    "breaker_storm": _rule_breaker_storm,
    "spill_thrash": _rule_spill_thrash,
    "stale_shard": _rule_stale_shard,
    "wedged_node": _rule_wedged_node,
    "fused_fallback_spike": _rule_fused_fallback_spike,
}
assert tuple(_RULES) == HEALTH_RULES


class HealthWatchdog:
    """Rule-driven SLO sweep over the history store. ``sweep()`` runs
    on the head's monitor tick right after ``HistoryStore.sample()``;
    a (rule, node) pair BECOMING active is flight-recorded
    (``health.<rule>``) and counted, active verdicts clear themselves
    when their condition stops holding."""

    def __init__(self, store: HistoryStore,
                 thresholds: dict | None = None):
        self.store = store
        self.thresholds = dict(thresholds or _thresholds_from_config())
        self._lock = lock_witness.Lock(
            "metrics_history.HealthWatchdog")
        self._active: dict[tuple, dict] = {}
        self._fired: deque = deque(maxlen=256)
        self._fired_total: dict[str, int] = {}

    def sweep(self, node_stats: dict,
              shard_rows: list | None = None) -> list:
        """One rule pass; returns the verdicts that became active."""
        from ray_tpu._private import flight_recorder

        ts = self.store._wall()
        hist = self.store.query(window_s=self.thresholds["window_s"])
        found: dict[tuple, dict] = {}
        for rule in HEALTH_RULES:
            for verdict in _RULES[rule](self.thresholds, hist,
                                        node_stats or {},
                                        shard_rows or [], ts):
                found[(verdict["rule"], verdict["node"])] = verdict
        with self._lock:
            new = [verdict for key, verdict in found.items()
                   if key not in self._active]
            self._active = found
            for verdict in new:
                self._fired.append(dict(verdict))
                self._fired_total[verdict["rule"]] = \
                    self._fired_total.get(verdict["rule"], 0) + 1
        for verdict in new:
            flight_recorder.record("health." + verdict["rule"],
                                   verdict["node"], verdict["value"])
        return new

    def report(self) -> dict:
        """The ``cluster_health`` RPC body: active verdicts, the
        recent fired ring, per-rule totals, the rule registry."""
        with self._lock:
            return {
                "armed": True,
                "verdicts": [dict(v) for v in self._active.values()],
                "fired": [dict(v) for v in self._fired],
                "fired_total": dict(self._fired_total),
                "rules": list(HEALTH_RULES),
                "window_s": self.thresholds["window_s"],
                "degraded": self.store.degraded(),
                "ts": self.store._wall(),
            }


def disarmed_history() -> dict:
    """The ``metrics_history`` RPC body on a disarmed head."""
    return {"armed": False, "interval_s": 0.0, "retention_s": 0.0,
            "window_s": 0.0, "ts": time.time(), "degraded": [],
            "nodes": {}}


def disarmed_health() -> dict:
    """The ``cluster_health`` RPC body on a disarmed head."""
    return {"armed": False, "verdicts": [], "fired": [],
            "fired_total": {}, "rules": list(HEALTH_RULES),
            "window_s": 0.0, "degraded": [], "ts": time.time()}
