"""Per-task/actor conda environments.

Reference: python/ray/_private/runtime_env/conda.py — either activate
an EXISTING named conda env, or create one per dependencies-spec hash
(cached per node, single-flight across processes). Activation follows
the pip backend's model: prepend the env's site-packages for the
task/actor's duration (worker_pool._runtime_env_ctx), no subprocess
re-exec.

Spec shapes (reference-compatible):
    runtime_env={"conda": "existing-env-name"}
    runtime_env={"conda": {"dependencies": ["python=3.12", "cowsay",
                                            {"pip": ["pkgA"]}]}}

The conda executable resolves from $RAY_TPU_CONDA_EXE, $CONDA_EXE, or
PATH; a missing conda fails the task with an actionable error (same as
the reference when no conda is installed).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess

from ray_tpu._private.runtime_env_pip import (
    _file_content_hash,
    ensure_env_single_flight,
    env_info,
)

_CONDA_ENV_ROOT = os.environ.get("RAY_TPU_CONDA_ENV_ROOT",
                                 "/tmp/ray_tpu_conda_envs")
# Conda solves + downloads can legitimately run far longer than a pip
# install; waiters must not time out while the builder's lock heartbeat
# shows it alive.
_CONDA_CREATE_TIMEOUT_S = 3600.0


def _conda_exe() -> str:
    exe = (os.environ.get("RAY_TPU_CONDA_EXE")
           or os.environ.get("CONDA_EXE")
           or shutil.which("conda"))
    if not exe:
        raise RuntimeError(
            "runtime_env={'conda': ...} requires a conda executable; "
            "none found via RAY_TPU_CONDA_EXE, CONDA_EXE, or PATH")
    return exe


def _iter_file_entries(spec: dict):
    """Local file paths anywhere in the dependencies tree (e.g. wheels
    inside a nested {'pip': [...]} entry)."""
    for dep in spec.get("dependencies", []):
        if isinstance(dep, dict):
            for sub in dep.get("pip", []):
                if isinstance(sub, str) and os.path.isfile(sub):
                    yield sub
        elif isinstance(dep, str) and os.path.isfile(dep):
            yield dep


def conda_env_hash(spec: dict) -> str:
    """Cache key: normalized spec PLUS the content of any local file
    entries — a wheel rebuilt at the same path must produce a new env,
    never serve the stale cached one (same convention as
    pip_env_hash)."""
    hasher = hashlib.sha1(json.dumps(spec, sort_keys=True).encode())
    for path in _iter_file_entries(spec):
        hasher.update(_file_content_hash(path).encode())
    return hasher.hexdigest()


# name -> env path: `conda env list` forks a subprocess; resolving on
# every task entry would put a CLI round trip on the hot path.
_named_env_memo: dict[str, str] = {}


def _named_env_path(exe: str, name: str) -> str:
    """Resolve a named env via `conda env list --json` (reference:
    conda.py get_conda_env_dir), memoized per process."""
    cached = _named_env_memo.get(name)
    if cached is not None and os.path.isdir(cached):
        return cached
    proc = subprocess.run([exe, "env", "list", "--json"],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"conda env list failed: {(proc.stderr or proc.stdout)[-1000:]}")
    envs = json.loads(proc.stdout).get("envs", [])
    for env_path in envs:
        # The root prefix is named "base" but its directory basename is
        # the install dir (e.g. /opt/miniconda3): base = the env NOT
        # under an envs/ parent (reference: conda.py get_conda_env_dir
        # special-cases base the same way).
        is_base = os.path.basename(os.path.dirname(env_path)) != "envs"
        if (os.path.basename(env_path) == name or env_path == name
                or (name == "base" and is_base)):
            _named_env_memo[name] = env_path
            return env_path
    raise RuntimeError(f"conda env {name!r} not found on this node")


def _create_from_spec(exe: str, target: str, spec: dict) -> None:
    """conda env create from an environment-dict written to a temp
    yaml-ish json file (conda accepts json env files)."""
    env_file = target + ".env.json"
    payload = {"name": os.path.basename(target),
               "dependencies": spec.get("dependencies", [])}
    if spec.get("channels"):
        payload["channels"] = spec["channels"]
    with open(env_file, "w") as f:
        json.dump(payload, f)
    try:
        proc = subprocess.run(
            [exe, "env", "create", "-p", target, "-f", env_file],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"conda env create failed: "
                f"{(proc.stderr or proc.stdout)[-4000:]}")
    finally:
        try:
            os.unlink(env_file)
        except OSError:
            pass  # env spec tmp already gone


def _check_python_compat(info: dict, spec) -> dict:
    """Activation happens IN-PROCESS (site-packages prepend, no
    re-exec), so an env built for another interpreter version would
    import cp3XX extension modules into a mismatched python. Fail
    actionably instead (the site-packages path encodes the version)."""
    import re
    import sys

    m = re.search(r"python(\d+)\.(\d+)", info["site_packages"])
    if m and (int(m.group(1)), int(m.group(2))) != (
            sys.version_info.major, sys.version_info.minor):
        raise RuntimeError(
            f"conda env {spec!r} targets python "
            f"{m.group(1)}.{m.group(2)} but this worker runs "
            f"{sys.version_info.major}.{sys.version_info.minor}; "
            f"in-process activation requires matching interpreter "
            f"versions (pin python={sys.version_info.major}."
            f"{sys.version_info.minor} in the env spec)")
    return info


def ensure_conda_env(spec) -> dict:
    """-> {"path", "python", "site_packages"} for ``spec``.

    Named envs must already exist on the node; dict specs are created
    once per content hash and cached (reference: conda.py caches envs
    under the session dir keyed by spec hash)."""
    exe = _conda_exe()
    if isinstance(spec, str):
        return _check_python_compat(
            env_info(_named_env_path(exe, spec)), spec)
    if not isinstance(spec, dict):
        raise ValueError(
            f"runtime_env['conda'] must be an env name or a "
            f"dependencies dict; got {type(spec).__name__}")
    target = os.path.join(_CONDA_ENV_ROOT, conda_env_hash(spec))
    return _check_python_compat(
        ensure_env_single_flight(
            target, lambda t: _create_from_spec(exe, t, spec),
            timeout_s=_CONDA_CREATE_TIMEOUT_S),
        spec)
