"""Unique identifiers for framework entities.

TPU-native analogue of the reference's binary ID system
(reference: src/ray/common/id.h, design_docs/id_specification.md): every
task, object, actor, node, job and placement group gets a globally unique
id. We use 16 random bytes (hex-printed) rather than the reference's
composed task-id+index scheme; object provenance is tracked explicitly by
the ownership table instead.
"""

from __future__ import annotations

import os
import threading


class _IdBlocks(threading.local):
    """Block-allocated random id bytes, one block per thread.

    A 100k-task submit burst pays one ``os.urandom`` syscall per
    ``_IDS_PER_BLOCK`` ids instead of one per id (two per task:
    TaskID + return ObjectID). Thread-local, so allocation is
    lock-free; the bytes still come from urandom, only the syscall is
    amortized."""

    _IDS_PER_BLOCK = 512

    def __init__(self):
        self.buf = b""
        self.pos = 0

    def take(self) -> bytes:
        pos = self.pos
        if pos >= len(self.buf):
            self.buf = os.urandom(16 * self._IDS_PER_BLOCK)
            pos = 0
        self.pos = pos + 16
        return self.buf[pos:pos + 16]


_ID_BLOCKS = _IdBlocks()


def _drop_id_block_after_fork() -> None:
    # A forked child (worker factory) inherits the forking thread's
    # buffered block; without this reset parent and child would mint
    # IDENTICAL "random" ids from the shared slice.
    _ID_BLOCKS.buf = b""
    _ID_BLOCKS.pos = 0


os.register_at_fork(after_in_child=_drop_id_block_after_fork)


class BaseID:
    """A 16-byte random identifier with a stable hex representation."""

    __slots__ = ("_bytes", "_hash")
    _NIL: bytes = b"\x00" * 16

    def __init__(self, id_bytes: bytes | None = None):
        if id_bytes is None:
            # Inlined _ID_BLOCKS.take(): this constructor is the
            # hottest line of a 100k-task submit burst (two fresh ids
            # per task) — the extra frame was measurable.
            blocks = _ID_BLOCKS
            pos = blocks.pos
            buf = blocks.buf
            if pos >= len(buf):
                buf = blocks.buf = os.urandom(16 * blocks._IDS_PER_BLOCK)
                pos = 0
            blocks.pos = pos + 16
            self._bytes = buf[pos:pos + 16]
            return
        if len(id_bytes) != 16:
            raise ValueError(f"{type(self).__name__} requires 16 bytes, got {len(id_bytes)}")
        self._bytes = id_bytes

    @classmethod
    def nil(cls):
        return cls(cls._NIL)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def is_nil(self) -> bool:
        return self._bytes == self._NIL

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        # Lazily cached: ids key half a dozen dict/set operations per
        # task on the submit path (store entry, lineage, task events,
        # cancel index), and the tuple build per hash was measurable
        # at 100k-submit bursts.
        try:
            return self._hash
        except AttributeError:
            h = self._hash = hash((type(self).__name__, self._bytes))
            return h

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class _Counter:
    """Thread-safe monotonically increasing counter (for sequence numbers)."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
