"""Unique identifiers for framework entities.

TPU-native analogue of the reference's binary ID system
(reference: src/ray/common/id.h, design_docs/id_specification.md): every
task, object, actor, node, job and placement group gets a globally unique
id. We use 16 random bytes (hex-printed) rather than the reference's
composed task-id+index scheme; object provenance is tracked explicitly by
the ownership table instead.
"""

from __future__ import annotations

import os
import threading


class BaseID:
    """A 16-byte random identifier with a stable hex representation."""

    __slots__ = ("_bytes",)
    _NIL: bytes = b"\x00" * 16

    def __init__(self, id_bytes: bytes | None = None):
        if id_bytes is None:
            id_bytes = os.urandom(16)
        if len(id_bytes) != 16:
            raise ValueError(f"{type(self).__name__} requires 16 bytes, got {len(id_bytes)}")
        self._bytes = id_bytes

    @classmethod
    def nil(cls):
        return cls(cls._NIL)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def is_nil(self) -> bool:
        return self._bytes == self._NIL

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class _Counter:
    """Thread-safe monotonically increasing counter (for sequence numbers)."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
