"""Actor execution runtime.

TPU-native analogue of the reference's actor machinery: per-actor ordered
submission queues (reference:
src/ray/core_worker/transport/sequential_actor_submit_queue.h vs
out_of_order_actor_submit_queue.h), server-side actor scheduling queue with
concurrency groups (transport/actor_scheduling_queue.h,
concurrency_group_manager.h), async actors on an event loop
(transport/fiber.h), and GCS-driven restart (gcs_actor_manager.h).

Each actor runs on a dedicated thread (max_concurrency=1 ⇒ strictly
ordered calls) or a small thread pool / asyncio loop for concurrent and
async actors. Actor resources are leased for the actor's lifetime.
"""

from __future__ import annotations

import asyncio
import inspect
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.scheduler import format_traceback
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorError,
    PendingCallsLimitExceeded,
    TaskCancelledError,
)


class _ExitActor(BaseException):
    """Raised by exit_actor() to unwind out of the running method."""


@dataclass
class _ActorCall:
    method_name: str
    args: tuple
    kwargs: dict
    return_ids: list[ObjectID]
    cancelled: bool = False
    # Absolute end-to-end deadline (time.time()); checked before the
    # method runs so a call whose budget died queued behind earlier
    # calls seals TaskTimeoutError instead of executing.
    deadline: "float | None" = None


def _call_deadline_error(call: _ActorCall, cls_name: str):
    """TaskTimeoutError for an actor call whose budget died queued
    (None while the deadline is still live) — shared by every actor
    executor (LocalActor / ProcessActor / RemoteActor)."""
    if call.deadline is None or time.time() <= call.deadline:
        return None
    from ray_tpu.exceptions import TaskTimeoutError

    return TaskTimeoutError(f"{cls_name}.{call.method_name}",
                            "actor_queue", call.deadline)


class LocalActor:
    """A live actor instance bound to an executor thread/loop."""

    def __init__(
        self,
        actor_id: ActorID,
        cls: type,
        init_args: tuple,
        init_kwargs: dict,
        runtime,
        *,
        max_concurrency: int = 1,
        max_restarts: int = 0,
        max_pending_calls: int = -1,
        creation_return_id: ObjectID | None = None,
        on_death: Callable[[ActorID, str], None] | None = None,
        on_restart: Callable[[ActorID], None] | None = None,
    ):
        self.actor_id = actor_id
        self._cls = cls
        self._init_args = init_args
        self._init_kwargs = init_kwargs
        self._runtime = runtime
        self._max_concurrency = max(1, max_concurrency)
        self._max_restarts = max_restarts
        self._max_pending_calls = max_pending_calls
        self._on_death = on_death
        self._on_restart = on_restart
        self._num_restarts = 0
        self._queue: queue.Queue[_ActorCall | None] = queue.Queue()
        self._pending = 0
        self._lock = threading.Lock()
        self._dead = False
        self._death_reason: str | None = None
        self._instance = None
        self._is_async = _has_async_methods(cls)
        self._creation_return_id = creation_return_id
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"ray_tpu-actor-{cls.__name__}", daemon=True)
        self._thread.start()

    # ----------------------------------------------------------------- calls

    def submit(self, call: _ActorCall) -> None:
        with self._lock:
            if self._dead:
                self._fail_call(call, ActorDiedError(
                    self.actor_id, self._death_reason or "actor has died"))
                return
            if 0 <= self._max_pending_calls <= self._pending:
                self._fail_call(call, PendingCallsLimitExceeded(
                    f"actor {self._cls.__name__} has {self._pending} pending calls"))
                return
            self._pending += 1
            # put() happens under the lock so _mark_dead's drain (same lock)
            # can never miss an in-flight call.
            self._queue.put(call)

    def _fail_call(self, call: _ActorCall, error: BaseException) -> None:
        for rid in call.return_ids:
            self._runtime.store.put_error(rid, error)

    # ------------------------------------------------------------- execution

    def _run(self) -> None:
        try:
            self._instance = self._cls(*self._init_args, **self._init_kwargs)
        except BaseException as exc:  # noqa: BLE001 — constructor failure kills actor
            self._mark_dead(f"constructor failed: {exc!r}")
            if self._creation_return_id is not None:
                self._runtime.store.put_error(
                    self._creation_return_id,
                    ActorError(exc, format_traceback(exc),
                               f"{self._cls.__name__}.__init__"))
            return
        if self._creation_return_id is not None:
            self._runtime.store.put(self._creation_return_id, None)
        self._started.set()
        if self._is_async:
            self._run_async_loop()
        elif self._max_concurrency > 1:
            self._run_threadpool()
        else:
            self._run_sequential()

    def _run_sequential(self) -> None:
        while True:
            call = self._queue.get()
            if call is None:
                return
            self._execute(call)
            # Unbind before re-blocking: a stale frame local would keep
            # the last call's args (and any nested ObjectRefs) alive.
            call = None

    def _run_threadpool(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=self._max_concurrency) as pool:
            while True:
                call = self._queue.get()
                if call is None:
                    return
                pool.submit(self._execute, call)
                call = None  # don't retain across the blocking get

    def _run_async_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        sem = asyncio.Semaphore(self._max_concurrency)

        async def drive():
            while True:
                call = await loop.run_in_executor(None, self._queue.get)
                if call is None:
                    return
                await sem.acquire()

                async def run_one(c=call):
                    try:
                        await loop.run_in_executor(None, lambda: None)  # yield
                        await self._execute_async(c)
                    finally:
                        sem.release()

                loop.create_task(run_one())

        try:
            loop.run_until_complete(drive())
        finally:
            pending = asyncio.all_tasks(loop)
            for task in pending:
                task.cancel()
            loop.run_until_complete(asyncio.gather(*pending, return_exceptions=True))
            loop.close()

    def _execute(self, call: _ActorCall) -> None:
        with self._lock:
            self._pending -= 1
        if call.cancelled:
            self._fail_call(call, TaskCancelledError())
            return
        expired = _call_deadline_error(call, self._cls.__name__)
        if expired is not None:
            self._fail_call(call, expired)
            return
        from ray_tpu._private import request_context

        ctx_token = request_context.set_deadline(call.deadline)
        try:
            method = getattr(self._instance, call.method_name)
            result = method(*call.args, **call.kwargs)
            self._store_result(call, result)
        except _ExitActor:
            self._store_result(call, None)
            self.kill("exit_actor() was called", no_restart=True)
        except BaseException as exc:  # noqa: BLE001 — reported on the ref
            self._fail_call(call, ActorError(
                exc, format_traceback(exc),
                f"{self._cls.__name__}.{call.method_name}"))
        finally:
            request_context.reset_deadline(ctx_token)

    async def _execute_async(self, call: _ActorCall) -> None:
        with self._lock:
            self._pending -= 1
        if call.cancelled:
            self._fail_call(call, TaskCancelledError())
            return
        expired = _call_deadline_error(call, self._cls.__name__)
        if expired is not None:
            self._fail_call(call, expired)
            return
        from ray_tpu._private import request_context

        ctx_token = request_context.set_deadline(call.deadline)
        try:
            method = getattr(self._instance, call.method_name)
            result = method(*call.args, **call.kwargs)
            if inspect.isawaitable(result):
                result = await result
            self._store_result(call, result)
        except _ExitActor:
            self._store_result(call, None)
            self.kill("exit_actor() was called", no_restart=True)
        except BaseException as exc:  # noqa: BLE001
            self._fail_call(call, ActorError(
                exc, format_traceback(exc),
                f"{self._cls.__name__}.{call.method_name}"))
        finally:
            request_context.reset_deadline(ctx_token)

    def _store_result(self, call: _ActorCall, result: Any) -> None:
        store = self._runtime.store
        if len(call.return_ids) == 1:
            store.put(call.return_ids[0], result)
        elif len(call.return_ids) > 1:
            values = list(result) if result is not None else [None] * len(call.return_ids)
            for rid, value in zip(call.return_ids, values):
                store.put(rid, value)

    # ----------------------------------------------------------------- death

    def kill(self, reason: str = "killed via kill()", no_restart: bool = True) -> None:
        restartable = (not no_restart) and self._num_restarts < self._max_restarts
        # A restarting actor keeps its resource lease and GCS liveness, so
        # on_death (which releases the lease) only fires on permanent death.
        self._mark_dead(reason, notify=not restartable)
        self._queue.put(None)  # unblock executor loop
        if restartable:
            self._restart()

    def _mark_dead(self, reason: str, notify: bool = True) -> None:
        with self._lock:
            if self._dead:
                return
            self._dead = True
            self._death_reason = reason
            # Fail everything still queued.
            drained: list[_ActorCall] = []
            try:
                while True:
                    item = self._queue.get_nowait()
                    if item is not None:
                        drained.append(item)
            except queue.Empty:
                pass
            self._pending = 0
        for call in drained:
            self._fail_call(call, ActorDiedError(self.actor_id, reason))
        if notify and self._on_death is not None:
            self._on_death(self.actor_id, reason)

    def _restart(self) -> None:
        """Recreate the instance (reference: GcsActorManager restart path)."""
        with self._lock:
            self._num_restarts += 1
            self._dead = False
            self._death_reason = None
        self._instance = None
        self._started.clear()
        self._creation_return_id = None
        self._thread = threading.Thread(
            target=self._run, name=f"ray_tpu-actor-{self._cls.__name__}-r{self._num_restarts}",
            daemon=True)
        self._thread.start()
        if self._on_restart is not None:
            self._on_restart(self.actor_id)

    def is_dead(self) -> bool:
        with self._lock:
            return self._dead

    def wait_started(self, timeout: float | None = None) -> bool:
        return self._started.wait(timeout)


def _has_async_methods(cls: type) -> bool:
    return any(
        inspect.iscoroutinefunction(m)
        for _, m in inspect.getmembers(cls, predicate=inspect.isfunction)
    )


def exit_actor():
    """Terminate the current actor from inside a method.

    Reference: ray.actor.exit_actor (python/ray/actor.py).
    """
    raise _ExitActor()
