"""The driver/worker runtime and the core public API implementation.

TPU-native analogue of the reference's CoreWorker + worker.py pair:
- ``Runtime`` plays the role of CoreWorker (reference:
  src/ray/core_worker/core_worker.h:291 — SubmitTask/CreateActor/
  SubmitActorTask/Get/Put/Wait) plus the per-process singleton
  (core_worker_process.h).
- Module functions (``init``/``get``/``put``/``wait``/…) mirror
  python/ray/_private/worker.py:1219+ (ray.init), :2547 (get), :2679
  (put), :2744 (wait), :2890 (get_actor).

Execution modes: by default tasks run on dispatcher threads (lowest
latency, shared address space). With ``init(process_workers=N)`` tasks
run on a pool of N OS worker processes behind a cloudpickle
serialization boundary with shared-memory object transport
(ray_tpu._private.worker_pool + shm_store) — real CPU parallelism for
fan-out workloads. Actors opt into a dedicated worker process with
``@remote(process=True)``.
"""

from __future__ import annotations

import atexit
import collections
import concurrent.futures
import logging
import os
import queue
import threading
import time
from typing import Any, Iterable, Sequence

from ray_tpu._private import accelerators
from ray_tpu._private import dispatch_lanes
from ray_tpu._private import perf_plane as perf
from ray_tpu._private import scheduler as scheduler_mod
from ray_tpu._private import speculation as spec_mod
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.gcs import (
    ActorRecord,
    GlobalControlService,
    JobRecord,
    NodeRecord,
    TaskEvent,
)
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef, resolve_args
from ray_tpu._private.object_store import ObjectStore, ReferenceCounter
from ray_tpu._private.placement_groups import PlacementGroupManager
from ray_tpu._private.scheduler import (
    BlockedResourceContext,
    ClusterState,
    Dispatcher,
    NodeState,
    format_traceback,
)
from ray_tpu._private.task import SchedulingStrategy, TaskSpec
from ray_tpu._private.actor_runtime import LocalActor, _ActorCall
from ray_tpu.util import tracing
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    SystemOverloadedError,
    TaskCancelledError,
    TaskError,
    TaskTimeoutError,
)

logger = logging.getLogger("ray_tpu")

_runtime_env_warned = False

# Bounds for the serialized-args memo (_convert_remote_args): only
# argument tuples made of small immutables are keyed by VALUE — safe to
# share one framed blob across tasks because nothing can mutate them
# and they can never contain an ObjectRef.
_ARG_CACHE_MAX_ENTRIES = 512
_ARG_CACHE_MAX_STR = 256
_ARG_CACHE_MAX_BLOB = 4096


def _simple_arg(value, depth: int = 0) -> bool:
    t = type(value)
    if t is int or t is float or t is bool or value is None:
        return True
    if t is str or t is bytes:
        return len(value) <= _ARG_CACHE_MAX_STR
    if t is tuple and depth < 2 and len(value) <= 8:
        return all(_simple_arg(v, depth + 1) for v in value)
    return False


# Columnar submit eligibility: exact scalar types only at the top
# level (the raw codec's shape minus containers — container args keep
# the classic ring path, whose pickle-time machinery they may need).
_COL_ARG_TYPES = frozenset((int, float, bool, str, bytes, type(None)))

# Counter-key registries for execution_pipeline_stats()'s driver-side
# submit/dispatch groups (the analysis counter-keys pass matches them
# against the builder and metrics_agent exports them as the
# ray_tpu_node_submit / ray_tpu_node_dispatch families).
SUBMIT_STAT_KEYS = (
    "ring_submits", "flushes", "flush_tasks", "ring_full_waits",
    "buffered_cancels", "arg_cache_hits", "col_submits",
    "col_flush_tasks", "flush_wall_us",
)
DISPATCH_STAT_KEYS = (
    "batches", "batch_tasks", "singles", "batch_overcommit",
    "deadline_sweeps", "lanes", "lane_dispatches", "lane_tasks",
    "lane_busy_us", "lane_overcommits", "col_groups",
    "lane_outstanding",
)


def _warn_runtime_env_ignored(context: str) -> None:
    """runtime_env only takes effect across a process boundary (pool
    workers / process actors); warn once when it is silently dropped."""
    global _runtime_env_warned
    if _runtime_env_warned:
        return
    _runtime_env_warned = True
    logger.warning(
        "runtime_env is ignored for thread-mode execution (%s): "
        "env_vars/working_dir need a process boundary — enable the "
        "worker pool (init(process_workers=N)) or use process=True "
        "actors", context)

_runtime_lock = threading.Lock()
_runtime: "Runtime | None" = None


class _DaemonPool:
    """Fixed-size pool of daemon threads draining a work queue.

    Replaces thread-per-actor spawning on the submission path:
    ``threading.Thread.start`` blocks until the new thread's bootstrap
    runs, which costs tens of milliseconds per call once the box has
    hundreds of runnable threads — at a 100-actor creation wave those
    stalls serialize and dominate the wave (measured ~40ms/actor).
    A stdlib ThreadPoolExecutor is unsuitable here: its workers are
    non-daemon and its atexit hook joins them, so one creation body
    parked in a lease wait would hang interpreter exit."""

    def __init__(self, max_workers: int, name: str):
        self._queue: "queue.Queue" = queue.Queue()
        self._max = max(1, max_workers)
        self._name = name
        self._spawned = 0
        self._idle = 0
        self._lock = threading.Lock()

    def submit(self, fn, *args) -> None:
        self._queue.put((fn, args))
        with self._lock:
            if self._idle == 0 and self._spawned < self._max:
                self._spawned += 1
                n = self._spawned
                threading.Thread(
                    target=self._work, daemon=True,
                    name=f"{self._name}-{n}").start()

    def _work(self) -> None:
        while True:
            with self._lock:
                self._idle += 1
            try:
                fn, args = self._queue.get()
            finally:
                with self._lock:
                    self._idle -= 1
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001 — bodies own their errors
                logger.exception("daemon-pool task failed (%s)", self._name)


class RuntimeContext:
    """Per-task/actor execution context (reference:
    python/ray/runtime_context.py)."""

    _tls = threading.local()

    @classmethod
    def current(cls) -> dict:
        return getattr(cls._tls, "ctx", None) or {}

    @classmethod
    def set(cls, **kwargs):
        cls._tls.ctx = kwargs

    @classmethod
    def clear(cls):
        cls._tls.ctx = None


class _SubmitRecord:
    """One buffered ``.remote()`` call: ids/refs were handed out
    inline; everything else is deferred to the submitter flush."""

    __slots__ = ("func", "args", "kwargs", "name", "num_returns",
                 "resources", "max_retries", "retry_exceptions",
                 "strategy", "runtime_env", "task_id", "return_ids",
                 "submit_ts", "trace_ctx", "cancelled", "state",
                 "deadline")

    # Lifecycle (state transitions under the ring condition lock):
    BUFFERED = 0   # in the ring; a cancel is handled ring-side
    DRAINING = 1   # claimed by a flush; a cancel is deferred to the
    #                flush's post-pass (the dispatcher knows it by then)
    SUBMITTED = 2  # out of the ring entirely


class _SubmitRing:
    """Bounded driver-side submit ring (the tentpole of the pipelined
    submit path): ``.remote()`` pushes a lightweight record and returns
    its pre-allocated refs; a dedicated submitter thread drains
    flushes, amortizing TaskSpec build, store/lineage/GCS record-
    keeping and the scheduler wakeup across a whole flush
    (Runtime._flush_submits). A full ring blocks the submitter —
    backpressure, never loss."""

    def __init__(self, runtime, capacity: int, flush_max: int):
        self._runtime = runtime
        self._capacity = max(2, int(capacity))
        self._flush_max = max(1, int(flush_max))
        self._cond = threading.Condition()
        self._ring: collections.deque = collections.deque()
        self._by_rid: dict = {}  # return ObjectID -> record (pre-SUBMITTED)
        self._stop = False
        self._parked = False
        # Test seam: clearing the gate holds the drain so races against
        # BUFFERED records (cancel, overflow) are deterministic.
        self._gate = threading.Event()
        self._gate.set()
        self.submits = 0
        self.flushes = 0
        self.flush_tasks = 0
        self.ring_full_waits = 0
        self.buffered_cancels = 0
        self._thread = threading.Thread(
            target=self._drain_loop, daemon=True, name="ray_tpu-submitter")
        self._thread.start()

    def holds(self, object_id) -> bool:
        """True while ``object_id`` belongs to a not-yet-dispatched
        buffered submit (attach_future treats those as pending)."""
        with self._cond:
            return object_id in self._by_rid

    def push(self, rec: _SubmitRecord) -> None:
        with self._cond:
            if len(self._ring) >= self._capacity:
                self.ring_full_waits += 1
                while len(self._ring) >= self._capacity and not self._stop:
                    self._cond.wait(0.1)
            self._ring.append(rec)
            for rid in rec.return_ids:
                self._by_rid[rid] = rec
            self.submits += 1
            if self._parked:
                self._cond.notify_all()

    def cancel(self, object_id) -> "_SubmitRecord | None":
        """Flag a buffered/draining submit cancelled. Returns the
        record when the ring owns the cancel (caller does nothing
        more): BUFFERED records are sealed with TaskCancelledError
        right here; DRAINING ones are cancelled by the flush's
        post-pass once the dispatcher knows them. None => unknown to
        the ring — the caller falls through to the dispatcher."""
        with self._cond:
            rec = self._by_rid.get(object_id)
            if rec is None:
                return None
            if rec.cancelled:
                return rec  # second cancel of the same ref: a no-op
            rec.cancelled = True
            buffered = rec.state == _SubmitRecord.BUFFERED
            if buffered:
                self.buffered_cancels += 1
        if buffered:
            # The flush skips cancelled BUFFERED records entirely, so
            # this is the one place their error is sealed.
            self._runtime._seal_cancelled_submit(rec)
        return rec

    def _aux_depth(self) -> int:
        """Columnar records buffered alongside the classic ring (the
        submitter thread drains both)."""
        return len(self._runtime._col_buf)

    def kick(self) -> None:
        """Wake a parked drain loop after a lock-free columnar push
        (the parked-flag read costs nothing during a burst)."""
        if self._parked:
            with self._cond:
                self._cond.notify_all()

    def col_backpressure(self) -> None:
        """Bounded blocking for a full columnar buffer — same
        semantics as a full ring: the submitter waits, never drops."""
        with self._cond:
            if self._aux_depth() < self._capacity:
                return
            self.ring_full_waits += 1
            while self._aux_depth() >= self._capacity \
                    and not self._stop:
                self._cond.wait(0.1)

    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while not self._ring and not self._aux_depth() \
                        and not self._stop:
                    self._parked = True
                    try:
                        self._cond.wait(timeout=0.2)
                    finally:
                        self._parked = False
                if not self._ring and not self._aux_depth() \
                        and self._stop:
                    return
            # Test seam sits between wake and claim so a cleared gate
            # deterministically holds records in the BUFFERED state.
            self._gate.wait()
            # Adaptive accumulation: while a BURST is in progress
            # (dozens already buffered and more arriving), briefly
            # yield so the producer fills a whole flush instead of
            # ping-ponging the GIL with it record-for-record — on a
            # busy box this is the difference between the submitter
            # and the .remote() loop splitting one core 50/50 and the
            # loop running hot. A lone interactive submit (small
            # depth) flushes immediately; the linger is bounded so a
            # stalling producer can never hold a batch hostage.
            if len(self._ring) + self._aux_depth() >= 64:
                deadline = time.monotonic() + 0.05
                last_depth = -1
                stalls = 0
                while not self._stop:
                    depth = len(self._ring) + self._aux_depth()
                    if depth >= self._flush_max \
                            or time.monotonic() >= deadline:
                        break
                    if depth == last_depth:
                        # One stalled tick can just be the producer
                        # losing the GIL to a runner/daemon burst;
                        # only a SUSTAINED stall ends the linger —
                        # bigger flushes mean deeper dispatch slices.
                        stalls += 1
                        if stalls >= 2:
                            break
                    else:
                        stalls = 0
                    last_depth = depth
                    time.sleep(0.002)
            # Columnar records flush first (their own groups, one lock
            # pass); failures there seal errors per record, never kill
            # the drain thread.
            if self._aux_depth():
                try:
                    self._runtime._flush_columnar(self)
                except BaseException:  # noqa: BLE001 — never die
                    logger.exception("columnar flush failed")
            with self._cond:
                n = min(len(self._ring), self._flush_max)
                batch = [self._ring.popleft() for _ in range(n)]
                self._cond.notify_all()  # unblock backpressured pushers
            if not batch:
                continue
            try:
                self._runtime._flush_submits(self, batch)
            except BaseException as exc:  # noqa: BLE001 — never die
                logger.exception("submit flush failed")
                for rec in batch:
                    with self._cond:
                        for rid in rec.return_ids:
                            self._by_rid.pop(rid, None)
                        already = rec.cancelled \
                            and rec.state == _SubmitRecord.BUFFERED
                        rec.state = _SubmitRecord.SUBMITTED
                    if not already:
                        for rid in rec.return_ids:
                            self._runtime.store.put_error(rid, exc)
            with self._cond:
                self.flushes += 1
                self.flush_tasks += n

    def depth(self) -> int:
        with self._cond:
            return len(self._ring) + self._aux_depth()

    def stop(self) -> None:
        """Flush whatever is buffered, then join the submitter."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._gate.set()
        self._thread.join(timeout=10.0)


class Runtime:
    """Everything a node/driver needs: store, control plane, scheduler."""

    def __init__(
        self,
        num_cpus: float | None = None,
        num_tpus: float | None = None,
        resources: dict[str, float] | None = None,
        object_store_memory: int | None = None,
        namespace: str = "default",
        process_workers: int | None = None,
        metrics_port: int | None = None,
        dashboard_port: int | None = None,
        address: str | None = None,
    ):
        cfg = GLOBAL_CONFIG
        self.namespace = namespace
        self.job_id = JobID()
        # Always-on performance plane: arm/disarm from the (possibly
        # system_config-overridden) knob, and clear the previous
        # session's histograms — an init/shutdown cycle must not
        # replay old latencies into this session's scrape.
        perf.init_from_config()
        perf.reset()
        # Locality-/load-aware placement + straggler speculation: arm
        # the module gates from the (possibly system_config-overridden)
        # knobs — same discipline as the perf plane above.
        scheduler_mod.init_sched_from_config()
        spec_mod.init_from_config()
        # Fused in-daemon execution + raw small-immutable framing:
        # driver-side module gates (daemons and pool workers re-arm
        # from config/env at their own import).
        from ray_tpu._private import node_executor as node_executor_mod
        from ray_tpu._private import serialization as serialization_mod

        node_executor_mod.init_fused_from_config()
        serialization_mod.init_raw_from_config()
        # Watermark-driven spill tier (spill_manager.py): arm the
        # module gate; the managers themselves attach to the stores
        # further down (after the lease tables they filter on exist).
        from ray_tpu._private import spill_manager as spill_mod

        spill_mod.init_from_config()
        # Driver-side flight recorder: ring only (no flusher thread,
        # no per-driver files) — `ray_tpu debug` reads it live.
        from ray_tpu._private import flight_recorder

        flight_recorder.install("driver")
        # Connected-cluster mode: register this driver with an external
        # head GCS (python -m ray_tpu start --head) and mirror its node
        # table into nodes()/state listings. Task execution stays local
        # to this driver's runtime; the control plane is cluster-wide.
        self.gcs_client = None
        self._node_agent = None
        if address:
            from ray_tpu._private.node import NodeAgent
            from ray_tpu._private.rpc import MuxRpcClient, RpcError

            # Pipelined head-GCS client: the watcher's long-poll sync,
            # location flushes, named-actor publication and KV traffic
            # ride one socket concurrently instead of serializing under
            # a per-call lock (reference: gRPC channels multiplex every
            # GCS service call).
            self.gcs_client = MuxRpcClient(address, timeout_s=60.0)
            self.gcs_client.on_reply_meta = self._on_gcs_reply_meta
            try:
                self._node_agent = NodeAgent(
                    address,
                    {"CPU": float(num_cpus if num_cpus is not None
                                  else cfg.num_cpus)},
                    labels={"node_role": "driver"},
                    usage_fn=self.available_resources)
            except (RpcError, OSError) as exc:
                self.gcs_client.close()
                self.gcs_client = None
                raise ConnectionError(
                    f"cannot connect to ray_tpu head at {address}: "
                    f"{exc}") from exc
        self.gcs = GlobalControlService()
        if self.gcs_client is not None:
            # Mirror local actor lifecycle to the head's cluster actor
            # registry (queued here, flushed by the node watcher).
            self.gcs.pubsub.subscribe("actors", self._queue_actor_mirror)
        self.store = ObjectStore(
            memory_limit_bytes=(object_store_memory
                                or cfg.object_store_memory_mb * 1024 * 1024),
            spill_dir=cfg.object_spilling_dir,
        )
        self.reference_counter = ReferenceCounter(self.store)
        self.cluster = ClusterState(spread_threshold=cfg.scheduler_spread_threshold)
        self.dispatcher = Dispatcher(self.cluster, self.store)
        # Overload-control counters (under _fault_lock, surfaced via
        # fault_stats): deadline-sealed tasks and admission sheds.
        self._task_timeouts = 0
        self._admission_shed = 0
        self.dispatcher.set_deadline_hook(self._seal_deadline)
        # Locality-aware placement inputs: the dispatcher asks this
        # hook for byte-weighted argument residency per admission
        # (scheduler.LOCALITY_ON gates every call). The threshold is
        # cached here so the dispatch hot path never takes the config
        # lock per task.
        self._locality_min_bytes = int(cfg.locality_min_arg_kb) * 1024
        # Learned residency: args >= the threshold accrue the nodes
        # that executed tasks consuming them (a pulled copy is cached
        # there) — bounded LRU. Plus the head ObjectDirectory's
        # multi-holder view, synced by the node watcher.
        self._arg_locality: collections.OrderedDict = \
            collections.OrderedDict()
        self._arg_locality_lock = threading.Lock()
        self._holder_cache: dict = {}
        # {object hex -> node hex} of holders whose copy is currently
        # on their disk tier (spill-aware locality discount).
        self._spilled_holders: dict = {}
        self._sched_feed_at = 0.0
        self.dispatcher.set_locality_hook(self._locality_for_spec)
        # Straggler speculation: driver-side watcher comparing each
        # in-flight task's elapsed wall against the perf plane's
        # per-function p99 (speculation.py); only exists while armed.
        self._spec_watcher = None
        if spec_mod.SPEC_ON:
            self._spec_watcher = spec_mod.SpeculationWatcher(self)
        self.placement_groups = PlacementGroupManager(self.cluster, self.store)
        self._actors: dict[ActorID, LocalActor] = {}
        # Signalled whenever an actor lands in _actors: submit queues
        # block on it instead of spin-polling (hundreds of concurrent
        # creations would otherwise busy-wake the GIL thousands of
        # times a second).
        self._actors_changed = threading.Condition()
        self._actor_queues: dict[ActorID, Any] = {}
        # Actor-creation bodies (lease + handle construction) run on a
        # shared pool instead of a thread per .remote(): at creation
        # waves, per-actor Thread.start stalls (~tens of ms each under
        # load) otherwise serialize on the submitting thread. Bodies
        # can park in lease waits, so the pool is deep; beyond it,
        # creations queue FIFO — a saner regime than 1000 unthrottled
        # creation threads anyway.
        self._actor_create_pool = _DaemonPool(64, "ray_tpu-actor-create")
        # Separate tiny pool for plain Thread.start offloads: those
        # must never queue behind parked creation bodies.
        self._thread_start_pool = _DaemonPool(4, "ray_tpu-thread-start")
        self._foreign_proxies: dict[tuple[str, str], Any] = {}
        self._actor_leases: dict[ActorID, tuple[NodeID, dict, Any]] = {}
        # (deadline, [refs]) grace pins for nested args of in-flight
        # submissions (see _pin_nested_arg_refs).
        self._arg_pin_pen: collections.deque = collections.deque()
        self._placement_record_lock = threading.Lock()
        self._futures_lock = threading.Lock()
        self._futures: dict[ObjectID, list[concurrent.futures.Future]] = {}
        self.store.add_seal_listener(self._resolve_futures)
        self._task_counter = 0

        # Multiprocess worker pool (opt-in): serialization boundary +
        # shared-memory transport; see worker_pool.py.
        from ray_tpu._private.shm_store import ShmClient, ShmDirectory

        import weakref

        self.shm_directory = ShmDirectory()
        self.shm_client = ShmClient()
        self.worker_pool = None
        self._promote_lock = threading.Lock()
        # Native shared arena (plasma-lite, _native/plasma_store.cpp):
        # the driver owns it; pool workers attach via RAY_TPU_ARENA_NAME.
        # Best-effort — without a C++ toolchain everything stays on the
        # segment-per-object path.
        self.arena = None
        arena_bytes = int(cfg.object_arena_bytes or 0)
        if arena_bytes > 0:
            from ray_tpu._private.arena_store import (
                ArenaStore,
                default_arena_name,
            )

            self.arena = ArenaStore.create(default_arena_name(), arena_bytes)
            if self.arena is not None:
                os.environ["RAY_TPU_ARENA_NAME"] = self.arena.name
                os.environ["RAY_TPU_ARENA_MAX"] = str(
                    int(cfg.object_arena_max_object_bytes))
                self.shm_client.set_arena(self.arena)
                self.shm_directory.set_arena(self.arena)
        self._func_blobs: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        pool_size = (process_workers if process_workers is not None
                     else cfg.worker_pool_size)
        self.log_monitor = None
        self.memory_monitor = None
        # Nested-submission plumbing: pool workers call the public API
        # back through this client server (reference: every Ray worker is
        # a full CoreWorker, core_worker.h:291); blocked nested gets ship
        # a task token so the owning task's CPU is released while waiting.
        self.worker_client_server = None
        self._inflight_blocks: dict[str, BlockedResourceContext] = {}
        self._inflight_blocks_lock = threading.Lock()
        # The client server backs nested submission from worker
        # processes (pool workers and process actors) and fronts this
        # driver's actors for other drivers in a connected cluster. It
        # starts eagerly in connected mode (named-actor publication
        # needs its address); otherwise lazily at the first process
        # spawn, so thread-only runtimes pay nothing.
        if self.gcs_client is not None:
            self.ensure_client_server()
        if pool_size and pool_size > 0:
            self.ensure_client_server()
            from ray_tpu._private.worker_pool import WorkerPool

            # Worker stdout/stderr -> per-worker files; the log monitor
            # tails them back to the driver console (reference:
            # log_monitor.py).
            if cfg.log_to_driver:
                import tempfile
                import uuid

                # Unique per SESSION (not just pid): an init/shutdown
                # cycle in one process must not replay or append to the
                # previous session's worker logs.
                log_dir = os.path.join(
                    tempfile.gettempdir(),
                    f"ray_tpu_session_{os.getpid()}_"
                    f"{uuid.uuid4().hex[:6]}", "logs")
                os.environ["RAY_TPU_WORKER_LOG_DIR"] = log_dir
                from ray_tpu._private.log_monitor import LogMonitor

                self.log_monitor = LogMonitor(
                    log_dir,
                    context_fn=self._worker_log_context).start()
            self.worker_pool = WorkerPool(
                int(pool_size), self.shm_directory, self.shm_client)
            refresh_ms = int(cfg.memory_monitor_refresh_ms or 0)
            if refresh_ms > 0:
                from ray_tpu._private.memory_monitor import MemoryMonitor

                self.memory_monitor = MemoryMonitor(
                    self, threshold=float(cfg.memory_usage_threshold),
                    period_s=refresh_ms / 1000.0).start()

        # Lineage + recovery + node health (reference:
        # object_recovery_manager.h:41, gcs_health_check_manager.h:39).
        from ray_tpu._private.recovery import (
            LineageTable,
            NodeHealthMonitor,
            ObjectRecoveryManager,
        )

        self.lineage = LineageTable(cfg.lineage_table_max_entries)
        self.recovery = ObjectRecoveryManager(self)
        # Serialized-args memo for the remote dispatch path: repeated
        # identical small-immutable argument tuples reuse one framed
        # blob instead of re-pickling per task (function blobs already
        # intern via _func_blobs; args did not).
        self._arg_blob_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._arg_blob_lock = threading.Lock()
        self.arg_cache_hits = 0
        # Columnar submit records (dispatch_lanes.py, ISSUE 15):
        # eligible .remote() calls append ONE tuple to this lock-free
        # buffer; the ring's flush thread drains it into per-template
        # ColumnarGroups for the dispatch lanes. _col_index maps every
        # in-flight columnar return id to its state (the record's
        # TaskID while buffered, then its group) for cancel /
        # attach_future / lazy expansion; _col_lock serializes flush
        # claims against cancels — the submit hot path never takes it.
        dispatch_lanes.init_from_config()
        self._col_buf: collections.deque = collections.deque()
        self._col_index: dict = {}
        self._col_lock = threading.Lock()
        self._lanes = None
        self._col_submits = 0
        self._col_flush_tasks = 0
        self._col_buffered_cancels = 0
        self._flush_wall_us = 0
        # Pipelined submission: .remote() returns pre-allocated refs and
        # defers the per-task record-keeping to the ring's flush thread.
        self._submit_ring = None
        if bool(cfg.submit_pipeline):
            self._submit_ring = _SubmitRing(
                self, int(cfg.submit_ring_size), int(cfg.submit_flush_max))
        self._object_locations: dict[ObjectID, NodeID] = {}
        # RLock: _forget_object can re-enter from ObjectRef.__del__ (GC
        # may fire while _record_location holds this lock).
        self._locations_lock = threading.RLock()
        # Location deltas pending publication to the head's object-
        # location table (reference: ownership_based_object_directory.h;
        # flushed in batches by the node watcher).
        self._loc_dirty_adds: dict[str, str] = {}
        self._loc_dirty_removes: set[str] = set()
        self._loc_keepalive = 0.0
        # Epoch fencing (connected mode): the head's incarnation epoch
        # observed on reply metadata. Stamped on every control-plane
        # WRITE this driver publishes (locations, actors, PGs); a bump
        # or a typed StaleEpochError triggers a full re-publish under
        # the new epoch (_flush_control_mirror / location keepalive).
        self._gcs_epoch: int | None = None
        self._epoch_republish = False
        # Cluster actor-registry mirror: local actor lifecycle events
        # queue their ids here; the watcher flushes batched
        # actor_update upserts to the head (whose snapshot+WAL make
        # the registry durable). PG snapshots publish on version bumps.
        self._mirror_lock = threading.Lock()
        self._actor_dirty: set = set()
        self._pg_published_version = -1
        self._gcs_persist_cache: tuple = (0.0, None)
        self._gcs_shard_cache: tuple = (0.0, None)
        self._history_cache: tuple = (0.0, None, None)
        self._health_cache: tuple = (0.0, None)
        # Remote execution plane state (threads start at the end of
        # __init__, but callbacks may touch these during construction).
        self._remote_nodes: dict[NodeID, Any] = {}
        self._remote_nodes_lock = threading.Lock()
        self._remote_ever: set[NodeID] = set()
        # node -> consecutive absent-but-pinging sync passes (only the
        # watcher thread touches it; bounded by node_amnesia_max_passes).
        self._amnesia_misses: dict[NodeID, int] = {}
        self._remote_free_queue: list[tuple[NodeID, bytes]] = []
        self._remote_free_lock = threading.Lock()
        self._watcher_stop = threading.Event()
        self._node_watcher = None
        self._export_store = None
        self._export_directory = None
        self._obj_server = None
        self._export_addr = ""
        # Same-host plane, driver side: exported args above the map
        # threshold get a named-segment (or arena) twin that co-hosted
        # daemons map instead of chunk-pulling (same_host.py).
        from ray_tpu._private.same_host import LeaseTable, host_identity

        self.host_id = host_identity()
        self._export_sources: dict[bytes, tuple] = {}
        self._export_segments: dict[bytes, Any] = {}
        self._export_leases = LeaseTable()
        self._export_lock = threading.Lock()
        self._lease_sweep_at = 0.0
        self.same_host_copy_hits = 0  # driver-side mapped-copy fetches
        # Driver-side spill tier: the value store's heap copies move
        # to checksummed session-dir files past the high watermark
        # (their shm/arena twins freed with them — unleased victims
        # only), torn restores fall back to lineage reconstruction.
        self._export_spill_mgr = None
        # ObjectID -> monotonic stamp of the last worker-bound shm
        # promotion: the spiller must not free a segment an in-flight
        # pool frame is about to attach.
        self._recent_promotes: dict = {}
        if spill_mod.SPILL_ON:
            self.store.enable_managed_spill(
                leased_fn=self._spill_protected_ids,
                on_backing_free=self._on_value_spilled,
                on_torn=self._recover_torn_object)
            from ray_tpu._private.memory_monitor import (
                set_store_bytes_provider,
            )

            set_store_bytes_provider(self._resident_store_bytes)
        # Driver-side failure counters (fault_stats): batch entries
        # requeued invisibly after a daemon death.
        self._fault_lock = threading.Lock()
        self._fault_batch_requeues = 0
        # Fused in-daemon execution, as seen from this driver (the
        # batch RPCs' ("done", n, stats) replies): surfaced via
        # execution_pipeline_stats()["fused"].
        self._fused_runs = 0
        self._fused_tasks = 0
        self._fused_fallbacks = 0
        self._pkg_hashes: dict[str, str] = {}
        # Refcount-zero eviction must also drop directory + lineage
        # entries, or they leak for the runtime's lifetime.
        self.reference_counter.on_evict = self._forget_object
        # Grace pins expire on TIME, not on the next submission: an
        # idle driver must still let its last pens lapse so normal
        # refcounting can free the objects.
        threading.Thread(target=self._arg_pin_sweeper, daemon=True,
                         name="ray_tpu-arg-pin-sweeper").start()
        self.health_monitor = NodeHealthMonitor(
            self.gcs, period_s=cfg.health_check_period_ms / 1000.0,
            failure_threshold=cfg.health_check_failure_threshold,
            on_node_dead=self._on_node_dead)

        # Prometheus /metrics endpoint (opt-in via metrics_port; 0 picks
        # a free port — reference: _private/metrics_agent.py per node).
        self.metrics_agent = None
        if metrics_port is not None:
            from ray_tpu._private.metrics_agent import start_metrics_agent

            self.metrics_agent = start_metrics_agent(self, port=metrics_port)

        # HTTP dashboard (opt-in via dashboard_port; 0 picks a free
        # port — reference: dashboard/head.py).
        self.dashboard = None
        if dashboard_port is not None:
            from ray_tpu.dashboard import Dashboard, runtime_provider

            self.dashboard = Dashboard(
                runtime_provider(self), port=dashboard_port).start()

        # Head node: autodetect CPU and TPU resources.
        detected = accelerators.detect_resources()
        head_resources = {"CPU": float(num_cpus if num_cpus is not None else cfg.num_cpus)}
        if num_tpus is not None:
            head_resources["TPU"] = float(num_tpus)
        elif detected.get("TPU"):
            head_resources["TPU"] = detected["TPU"]
        head_resources.update(
            {k: v for k, v in detected.items() if k not in head_resources})
        if resources:
            head_resources.update({k: float(v) for k, v in resources.items()})
        self.head_node_id = self.add_node(head_resources, labels={"node_type": "head"})
        self.gcs.register_job(JobRecord(self.job_id))

        # Connected-cluster execution plane: mirror the GCS node table
        # into ClusterState so pick_node can choose worker daemons, and
        # dispatch to them over RPC (reference: the two-level scheduler —
        # cluster view + remote raylet lease, cluster_task_manager.h:42).
        if self.gcs_client is not None:
            # Driver-side object export server: driver-held task args
            # above the inline threshold are served from here so each
            # node pulls (and caches) them ONCE instead of the driver
            # re-shipping the bytes with every task (reference: plasma +
            # object manager — args are objects nodes fetch, not
            # payloads inlined per task).
            from ray_tpu._private.node import _own_address
            from ray_tpu._private.node_executor import (
                ChunkDirectory,
                NodeObjectStore,
            )
            from ray_tpu._private.rpc import RpcServer

            self._export_store = NodeObjectStore()
            if spill_mod.SPILL_ON:
                # Exported args ride the same tier: spilling a blob
                # frees its segment/arena twin (unleased only — the
                # lease filter covers co-hosted daemons mid-map).
                self._export_spill_mgr = \
                    self._export_store.enable_managed_spill(
                        leased_fn=self._export_leases.pinned_ids,
                        on_spilled=lambda key, _owner:
                            self._drop_export_source(key))
            self._export_directory = ChunkDirectory()
            self._obj_server = RpcServer(host="0.0.0.0", port=0)
            self._obj_server.register("ping", lambda: "pong")
            # Pooled: pipelined chunk pulls from many nodes interleave
            # instead of serializing on each connection's serve loop.
            self._obj_server.register(
                "fetch_object", self._export_fetch_object,
                concurrent="pooled")
            self._obj_server.register(
                "fetch_plan", self._export_fetch_plan,
                concurrent="pooled")
            self._obj_server.register(
                "unpin_object", self._export_leases.release)
            self._obj_server.start()
            self._export_addr = \
                f"{_own_address()}:{self._obj_server.port}"
            self._node_watcher = threading.Thread(
                target=self._watch_remote_nodes, daemon=True,
                name="ray_tpu-node-watcher")
            self._node_watcher.start()
            # Pipelined execute path: tasks claimed for one remote node
            # in a dispatch pass ride a single execute_task_batch RPC.
            self.dispatcher.set_batch_hooks(self._task_batch_key,
                                            self._run_task_batch)
            # Sharded dispatch lanes (ISSUE 15): columnar groups of
            # fused-eligible DEFAULT submits bypass the classic
            # dispatcher entirely — N lanes acquire whole per-node
            # allocation plans from the cluster ledger (one lock pass
            # per flush) and ship compact columnar batch RPCs.
            if dispatch_lanes.SHARD_ON:
                self._lanes = dispatch_lanes.DispatchLanes(
                    self.cluster, self._run_columnar_slice,
                    fallback=self._columnar_starved,
                    node_filter=self._columnar_node_filter)

    # ------------------------------------------------------ remote exec plane

    def _export_fetch_object(self, id_bytes: bytes, offset: int,
                             length: int):
        from ray_tpu._private.node_executor import wrap_chunk_reply

        reply = self._export_store.read_chunk(id_bytes, offset, length)
        return None if reply is None else wrap_chunk_reply(reply)

    def _export_fetch_plan(self, id_bytes: bytes,
                           puller_addr: str | None = None,
                           puller_host: str | None = None):
        """Transfer plan for a driver-exported object: (size, holders,
        map_source). Registers the puller so the NEXT puller fetches
        chunks from it too — the driver seeds a broadcast once and
        receivers relay (reference: the owner hands out locations via
        the object directory; data flows node-to-node). Co-hosted
        pullers instead get a map source + pin lease and move no bytes
        at all (same_host.py)."""
        from ray_tpu._private.node_executor import plan_holders
        from ray_tpu._private.same_host import map_enabled

        total = self._export_store.size(id_bytes)
        if total is None:
            return None
        map_info = None
        if puller_addr and puller_host and map_enabled() \
                and puller_host == self.host_id:
            map_info = self._grant_export_lease(id_bytes, puller_addr)
        reg_addr = None if map_info is not None else puller_addr
        return (total, plan_holders(
            self._export_directory, id_bytes, reg_addr, total), map_info)

    def _grant_export_lease(self, id_bytes: bytes,
                            holder: str) -> dict | None:
        with self._export_lock:
            source = self._export_sources.get(id_bytes)
        if source is None:
            return None
        kind, name, size = source[0], source[1], source[2]
        key = source[3] if len(source) > 3 else b""
        if kind == "arena":
            if self.arena is None or self.arena.pin(key) is None:
                return None
            arena = self.arena
            token = self._export_leases.grant(
                id_bytes, holder, on_release=lambda: arena.unpin(key))
        else:
            token = self._export_leases.grant(id_bytes, holder)
        return {"kind": kind, "name": name, "key": key, "size": size,
                "host": self.host_id, "token": token}

    def _register_export_source(self, id_bytes: bytes, header,
                                buffers, size: int):
        """Back a large export with named shared memory so same-host
        daemons map it. Returns the buffer the framed bytes were
        written into (a segment's memoryview), or None when the caller
        should keep a heap blob (plane off / segment unavailable).

        ≥ map threshold -> dedicated segment (consumers map zero-copy);
        below it but arena-sized -> the driver's arena (consumers take
        a cross-arena descriptor or one memcpy)."""
        from multiprocessing import shared_memory

        from ray_tpu._private import serialization
        from ray_tpu._private.same_host import (
            map_enabled,
            map_min_bytes,
        )
        from ray_tpu._private.shm_store import ShmObjectWriter

        if not map_enabled():
            return None
        if size >= map_min_bytes():
            try:
                seg = shared_memory.SharedMemory(create=True,
                                                 size=max(size, 1))
            except OSError:
                return None  # /dev/shm full: heap blob + chunked pull
            serialization.write_framed(seg.buf, header, buffers)
            with self._export_lock:
                self._export_sources[id_bytes] = ("seg", seg.name, size)
                self._export_segments[id_bytes] = seg
            return memoryview(seg.buf)[:size]
        if self.arena is not None and size <= int(
                GLOBAL_CONFIG.object_arena_max_object_bytes):
            # Arena twin under the object id — the same key the export
            # carries, so peers peek it by id after attaching. The
            # export store keeps its own heap copy (the arena twin is
            # evictable state; the store copy serves chunked pulls).
            adesc = ShmObjectWriter.put_arena_serialized(
                self.arena, id_bytes, header, buffers, size)
            if adesc is not None:
                with self._export_lock:
                    self._export_sources[id_bytes] = (
                        "arena", self.arena.name, size, id_bytes)
                buf = bytearray(size)
                serialization.write_framed(memoryview(buf), header,
                                           buffers)
                return bytes(buf)
        return None

    def _drop_export_source(self, id_bytes: bytes) -> None:
        """Free path: release peers' leases, then the backing shared
        memory. Unlink-while-mapped is safe for segments (POSIX keeps
        existing mappings); arena twins need their pin dropped before
        delete can take effect."""
        with self._export_lock:
            source = self._export_sources.pop(id_bytes, None)
            seg = self._export_segments.pop(id_bytes, None)
        if source is None:
            return
        self._export_leases.release_object(id_bytes)
        if source[0] == "arena" and self.arena is not None:
            self.arena.unpin(id_bytes)   # the seal_pinned creation ref
            self.arena.delete(id_bytes)
        if seg is not None:
            try:
                seg.unlink()
            except (OSError, FileNotFoundError):
                pass  # segment already unlinked by the tracker
            try:
                seg.close()
            except (BufferError, OSError):
                # An in-flight chunk read still views the mapping:
                # leak it until process exit rather than invalidating.
                from ray_tpu._private.shm_store import _defuse

                _defuse(seg)

    def _watch_remote_nodes(self) -> None:
        """Mirror the head's node table into ClusterState, reacting to
        membership PUSH events (the head's "nodes" pubsub channel —
        reference: GcsNodeManager broadcasts node-dead over pubsub)
        with a long-poll, plus a periodic resync as the safety net;
        each wake also flushes queued object frees and location
        deltas."""
        from ray_tpu._private.gcs_pubsub import GcsSubscriber
        from ray_tpu._private.rpc import (
            RpcError,
            RpcMethodError,
            call_with_retry,
        )

        subscriber = None
        try:
            subscriber = GcsSubscriber(
                self.gcs_client.address,
                ["nodes", "node_resources", "object_loss"])
        except Exception:  # noqa: BLE001 — pre-pubsub head: poll only
            subscriber = None
        last_sync = 0.0
        try:
            while not self._watcher_stop.is_set():
                events = []
                # Queued frees / dirty locations shorten the wait: the
                # flush cadence must not degrade to the full long-poll
                # interval while work is pending (the free queue is
                # bounded; slow flushing would overflow it 10x sooner).
                with self._remote_free_lock:
                    pending_frees = bool(self._remote_free_queue)
                with self._locations_lock:
                    dirty_locs = bool(self._loc_dirty_adds
                                      or self._loc_dirty_removes)
                poll_s = 0.5 if (pending_frees or dirty_locs) else 5.0
                if subscriber is not None:
                    try:
                        # Blocks server-side until a membership event
                        # (push) or the timeout.
                        events = subscriber.poll(timeout_s=poll_s)
                    except Exception:  # noqa: BLE001 — head gone
                        self._watcher_stop.wait(0.5)
                else:
                    self._watcher_stop.wait(0.5)
                if self._watcher_stop.is_set():
                    return
                # Syncer pushes: per-node availability deltas update the
                # scheduler's reported view directly — no list_nodes
                # round trip (reference: ray_syncer resource stream).
                membership_events = []
                for channel, message in events:
                    if channel == "node_resources":
                        try:
                            hex_id, available = message
                            self.cluster.update_reported(
                                NodeID(bytes.fromhex(hex_id)), available)
                        except Exception:  # noqa: BLE001 — malformed push
                            pass
                    elif channel == "object_loss":
                        # Head pruned the LAST holder of these objects
                        # (node death): rebuild from lineage now
                        # instead of waiting for a get() to trip over
                        # the dead holder.
                        try:
                            self._handle_object_loss(message)
                        except Exception:  # noqa: BLE001 — best-effort
                            logger.exception("object-loss push failed")
                    else:
                        membership_events.append((channel, message))
                try:
                    # Frees/location deltas flush every wake; the FULL
                    # node-table resync only on a MEMBERSHIP push event
                    # or the periodic safety net (a pre-pubsub head
                    # keeps the old per-wake cadence); resource deltas
                    # alone never trigger it.
                    self._flush_remote_frees()
                    self._flush_object_locations()
                    self._flush_control_mirror()
                    now = time.monotonic()
                    if scheduler_mod.LOCALITY_ON \
                            and now - self._sched_feed_at >= 2.0:
                        # Load-/locality-aware placement inputs: the
                        # node-stats ages + the holder table.
                        self._sched_feed_at = now
                        self._sync_sched_feed()
                    if (membership_events or subscriber is None
                            or now - last_sync >= 10.0):
                        # Idempotent GCS read on the shared retry
                        # policy: one dropped frame must not stall the
                        # node view a full poll interval.
                        self._sync_remote_nodes(call_with_retry(
                            self.gcs_client.call, "list_nodes",
                            attempts=2, timeout_s=10.0))
                        last_sync = now
                except (RpcError, RpcMethodError, OSError,
                        AttributeError):
                    continue  # head down / client mid-teardown: next pass
                except Exception:  # noqa: BLE001 — watcher must survive
                    logger.exception("remote node sync failed")
        finally:
            # Closed HERE, not in shutdown(): the watcher may still be
            # constructing/polling the subscriber when shutdown() runs,
            # and only this thread knows the final reference.
            if subscriber is not None:
                subscriber.close()

    def _sync_remote_nodes(self, nodes: list[dict]) -> None:
        from ray_tpu._private.node_executor import RemoteNodeHandle

        listed: dict[NodeID, dict] = {}
        for info in nodes:
            if info.get("executor_address"):
                listed[NodeID(bytes.fromhex(info["node_id"]))] = info

        # Reconcile disappearances: a node the head declared DEAD, or
        # whose executor address changed, is dropped; so is an old id
        # superseded by a re-registration under a fresh id (same
        # executor_address must not double-count capacity). A node
        # merely ABSENT from the table gets a direct-ping grace first:
        # a freshly restarted head starts with an empty table, and the
        # daemon (which keeps its node id across head restarts) may
        # simply not have re-registered yet — its in-flight work is
        # alive and must not be failed by head amnesia. The grace is
        # BOUNDED: a daemon that pings but stays absent from the head's
        # table past node_amnesia_max_passes consecutive sync passes is
        # partitioned from the control plane (it cannot re-register) —
        # keeping it schedulable forever would strand its results
        # outside the directory, so it is dropped like a dead node.
        with self._remote_nodes_lock:
            known = dict(self._remote_nodes)
        alive_addrs = {info["executor_address"] for nid, info
                       in listed.items() if info["alive"]}
        amnesia_candidates = []
        for node_id, handle in known.items():
            info = listed.get(node_id)
            superseded = (info is None
                          and handle.address in alive_addrs)
            declared_dead = info is not None and (
                not info["alive"]
                or info["executor_address"] != handle.address)
            if superseded or declared_dead:
                self._drop_remote_node(node_id)
            elif info is None:
                amnesia_candidates.append((node_id, handle))
            else:
                self._amnesia_misses.pop(node_id, None)
        if amnesia_candidates:
            # Direct-ping grace pings run CONCURRENTLY: after a head
            # restart with many genuinely dead daemons, serial 5s ping
            # timeouts would stall this watcher for minutes while dead
            # handles keep receiving (and failing) dispatches.
            from concurrent.futures import ThreadPoolExecutor

            max_passes = max(1, int(GLOBAL_CONFIG.node_amnesia_max_passes))
            with ThreadPoolExecutor(
                    max_workers=min(8, len(amnesia_candidates))) as tpe:
                alive_flags = list(tpe.map(
                    lambda nh: nh[1].ping(), amnesia_candidates))
            for (node_id, _), is_alive in zip(amnesia_candidates,
                                              alive_flags):
                misses = self._amnesia_misses.get(node_id, 0) + 1
                if not is_alive or misses > max_passes:
                    self._amnesia_misses.pop(node_id, None)
                    self._drop_remote_node(node_id)
                else:
                    self._amnesia_misses[node_id] = misses

        for node_id, info in listed.items():
            if not info["alive"]:
                continue
            with self._remote_nodes_lock:
                already = node_id in self._remote_nodes
            if already:
                # Safety net for the push channel: refresh the reported
                # availability from the table (a missed pubsub delta
                # must not wedge dispatch on a stale low-water mark).
                if info.get("available"):
                    self.cluster.update_reported(
                        node_id, info["available"])
                continue
            handle = RemoteNodeHandle(node_id, info["executor_address"])
            if not handle.ping():
                handle.close()
                continue
            with self._remote_nodes_lock:
                self._remote_nodes[node_id] = handle
                self._remote_ever.add(node_id)
            # Re-join after a transient drop keeps the old ledger (in-
            # flight task releases must balance); only genuinely new
            # nodes get a fresh NodeState.
            if not self.cluster.revive_node(node_id):
                self.cluster.add_node(NodeState(
                    node_id=node_id,
                    total=dict(info["resources"]),
                    available=dict(info["resources"]),
                    labels={**info.get("labels", {}), "remote": "1"},
                ))
            logger.info("remote node %s (%s) joined with %s",
                        info["node_id"][:8], info["executor_address"],
                        info["resources"])

    def _drop_remote_node(self, node_id: NodeID) -> None:
        with self._remote_nodes_lock:
            handle = self._remote_nodes.pop(node_id, None)
            alive = set(self._remote_nodes)
        if handle is None:
            return
        handle.close()
        # Busy-spillback avoid sets were computed against the OLD
        # membership: with this node gone they can exclude every
        # surviving candidate, leaving their tasks queued forever (the
        # spillback reset only re-evaluates on the NEXT bounce, which
        # an un-dispatchable task never gets).
        self.dispatcher.reset_unsatisfiable_avoids(alive)
        self._on_node_dead(node_id)

    def _flush_remote_frees(self) -> None:
        with self._remote_free_lock:
            queued, self._remote_free_queue = self._remote_free_queue, []
        if not queued:
            return
        by_node: dict[NodeID, list[bytes]] = {}
        for node_id, id_bytes in queued:
            by_node.setdefault(node_id, []).append(id_bytes)
        retained: list[tuple[NodeID, bytes]] = []
        for node_id, ids in by_node.items():
            with self._remote_nodes_lock:
                handle = self._remote_nodes.get(node_id)
            if handle is None:
                # Node transiently absent: keep the frees for its
                # return (its store only drops results on owner free).
                retained.extend((node_id, i) for i in ids)
                continue
            try:
                handle.free(ids)
            except Exception:  # noqa: BLE001 — best-effort, retry later
                retained.extend((node_id, i) for i in ids)
        if retained:
            with self._remote_free_lock:
                self._remote_free_queue.extend(retained)
                # Bounded: drop the oldest if a node never comes back.
                if len(self._remote_free_queue) > 100_000:
                    del self._remote_free_queue[:-50_000]

    def _materialize_value(self, object_id: ObjectID, value: Any) -> Any:
        """Resolve a RemoteBlob placeholder by chunked pull from the
        holding node; on failure fall back to lineage reconstruction
        (reference: pull via object directory, recovery via
        object_recovery_manager.h:41)."""
        from ray_tpu._private.node_executor import RemoteBlob, fetch_blob
        from ray_tpu._private import serialization

        if not isinstance(value, RemoteBlob):
            return value
        node_id = NodeID(bytes.fromhex(value.node_hex))
        with self._remote_nodes_lock:
            handle = self._remote_nodes.get(node_id)
        try:
            # Co-hosted holder: one memcpy out of its shared memory
            # beats a chunked pull (same_host.py); falls through to the
            # chunked path when no map lease is granted.
            from ray_tpu._private.same_host import (
                fetch_mapped_blob,
                map_enabled,
            )

            blob = None
            if map_enabled() and self._export_addr:
                call = (handle.pool.call if handle is not None else None)
                if call is not None:
                    blob = fetch_mapped_blob(
                        call, object_id.binary(), self._export_addr,
                        self.host_id)
                    if blob is not None:
                        self.same_host_copy_hits += 1
            if blob is not None:
                pass
            elif handle is not None:
                blob = handle.fetch(object_id.binary())
            else:
                from ray_tpu._private.rpc import RpcClient

                client = RpcClient(value.addr)
                try:
                    if map_enabled() and self._export_addr:
                        blob = fetch_mapped_blob(
                            client.call, object_id.binary(),
                            self._export_addr, self.host_id)
                        if blob is not None:
                            self.same_host_copy_hits += 1
                    if blob is None:
                        blob = fetch_blob(client, object_id.binary())
                finally:
                    client.close()
            real = serialization.deserialize_from_buffer(memoryview(blob))
        except Exception as exc:  # noqa: BLE001 — node gone: try lineage
            from ray_tpu.exceptions import ObjectLostError

            if not self.store.mark_lost(object_id):
                raise
            recovered = False
            try:
                recovered = self.recovery.recover(object_id)
            except Exception:  # noqa: BLE001
                pass
            if recovered:
                return self._materialize_value(
                    object_id, self.store.get(object_id))
            err = ObjectLostError(
                ObjectRef(object_id, _register=False),
                f"object {object_id.hex()} was on unreachable node "
                f"{value.node_hex[:8]} and has no lineage: {exc}")
            self.store.put_error(object_id, err)
            raise err from exc
        self.store.put(object_id, real)  # reseal with the local copy
        return real

    # ------------------------------------------------------------ spill tier

    _SHM_PROMOTE_GRACE_S = 30.0

    def _spill_protected_ids(self) -> set:
        """Id bytes the driver spiller must skip: export leases held
        by co-hosted daemons plus values promoted to worker-bound shm
        within the grace window (their frames may not have attached
        the segment yet)."""
        out = set(self._export_leases.pinned_ids())
        now = time.monotonic()
        with self._promote_lock:
            for oid in [o for o, at in self._recent_promotes.items()
                        if now - at > self._SHM_PROMOTE_GRACE_S]:
                del self._recent_promotes[oid]
            out.update(oid.binary() for oid in self._recent_promotes)
        return out

    def _on_value_spilled(self, object_id: ObjectID) -> None:
        """A driver-store value moved to the disk tier: free its
        shm/arena twin (the victim filter excluded leased ids, so no
        co-hosted daemon holds a pin; already-mapped segments stay
        valid past the unlink) and its export-plane state."""
        try:
            self.shm_directory.free(object_id)
        except Exception:  # noqa: BLE001 — backing free is best-effort
            pass
        self._drop_export_source(object_id.binary())

    def _recover_torn_object(self, object_id: ObjectID) -> None:
        """A managed spill file failed its checksum on restore: the
        store marked the entry lost — rebuild it from lineage (the
        getter is blocked on the reseal), or seal ObjectLostError so
        waiters fail typed instead of hanging."""
        from ray_tpu.exceptions import ObjectLostError

        from ray_tpu._private import flight_recorder

        flight_recorder.record("spill.torn", object_id.hex()[:16])
        recovered = False
        try:
            recovered = self.recovery.recover(object_id,
                                              reason="spill_torn")
        except Exception:  # noqa: BLE001 — fall through to the error
            pass
        if not recovered:
            self.store.put_error(object_id, ObjectLostError(
                ObjectRef(object_id, _register=False),
                f"object {object_id.hex()} spill file was torn and no "
                f"lineage can rebuild it"))

    def _resident_store_bytes(self) -> int:
        """Resident SPILLABLE bytes for admission's two-axis pressure
        classifier: the value store's heap usage plus exported blobs
        (both relieved by the spill tier, unlike true host RSS)."""
        total = self.store._memory_used  # int read, no lock needed
        if self._export_store is not None:
            total += getattr(self._export_store, "_primary_bytes", 0)
        return total

    def spill_stats(self) -> dict:
        """Driver-side spill tier counters (value store + export
        store), zero-valued when the tier is disarmed — the
        ``ray_tpu_spill_*`` /metrics families and the envelope's spill
        row read these."""
        from ray_tpu._private.spill_manager import merged_stats

        return merged_stats(getattr(self.store, "_spill", None),
                            self._export_spill_mgr)

    # -------------------------------------------------------------- cluster

    def add_node(self, resources: dict[str, float],
                 labels: dict[str, str] | None = None) -> NodeID:
        """Add a virtual node (reference: cluster_utils.Cluster.add_node)."""
        node_id = NodeID()
        state = NodeState(
            node_id=node_id,
            total=dict(resources),
            available=dict(resources),
            labels=labels or {},
        )
        self.cluster.add_node(state)
        self.gcs.register_node(NodeRecord(
            node_id=node_id, address=f"local://{node_id.hex()[:8]}",
            resources=dict(resources), labels=labels or {}))
        return node_id

    def remove_node(self, node_id: NodeID) -> None:
        self.cluster.remove_node(node_id)
        self.gcs.mark_node_dead(node_id)

    def kill_node(self, node_id: NodeID) -> None:
        """Chaos: simulate a node crash (reference:
        test_utils.NodeKillerActor, :1498). The health monitor stops
        heartbeating it; staleness then drives the normal death path
        (_on_node_dead) — detection, not fiat.
        """
        self.health_monitor.suppress(node_id)

    def _on_node_dead(self, node_id: NodeID) -> None:
        """Node death: remove from scheduling, lose its objects, rebuild
        what lineage allows (reference: GcsNodeManager node-dead
        broadcast + ObjectRecoveryManager re-execution)."""
        from ray_tpu.exceptions import ObjectLostError

        logger.warning("Node %s died; reconstructing its objects",
                       node_id.hex()[:8])
        from ray_tpu._private import flight_recorder

        flight_recorder.record("node.dead", node_id.hex()[:16])
        self.remove_node(node_id)
        # Queued tasks HARD-pinned to the dead node can never run; fail
        # them now instead of hanging their waiters forever (soft
        # affinity and unpinned tasks reschedule on survivors).
        for spec in self.dispatcher.fail_hard_affinity(node_id.hex()):
            err = TaskError(
                RuntimeError(
                    f"node {node_id.hex()[:8]} died and task "
                    f"{spec.name} is hard-pinned to it"),
                None, spec.name)
            for rid in spec.return_ids:
                self.store.put_error(rid, err)
        # Actors hosted on the dead node restart on a survivor (or die
        # permanently) — even parked ones with no call in flight
        # (reference: GcsActorManager restarts actors on node death).
        for actor in list(self._actors.values()):
            if getattr(actor, "node_id", None) == node_id:
                actor.notify_node_death(node_id)
        with self._locations_lock:
            lost = [oid for oid, nid in self._object_locations.items()
                    if nid == node_id]
            for oid in lost:
                del self._object_locations[oid]
        # Mark everything lost BEFORE recovering anything: recovery checks
        # is_lost() on dependencies, so a partially-marked set would let a
        # parent resubmit against a dep about to vanish.
        marked = [oid for oid in lost if self.store.mark_lost(oid)]
        for oid in marked:
            try:
                if not self.recovery.recover(oid):
                    # _register=False: the error lives inside the entry it
                    # describes — a registered ref would pin the refcount
                    # above zero forever.
                    self.store.put_error(oid, ObjectLostError(
                        ObjectRef(oid, _register=False),
                        f"object {oid.hex()} was on dead node "
                        f"{node_id.hex()[:8]} and has no lineage"))
            except Exception:  # noqa: BLE001 — one object must not strand
                logger.exception("failed to handle loss of object %s",
                                 oid.hex())

    def _handle_object_loss(self, obj_hexes) -> None:
        """Push-path twin of _on_node_dead's object handling: the head
        pruned the LAST holder of these objects from its directory (the
        holding node died). Only objects this driver still tracks as
        remote placeholders react — a locally materialized copy
        survives its producer's node, and foreign owners' ids simply
        don't resolve here."""
        from ray_tpu._private.node_executor import RemoteBlob
        from ray_tpu.exceptions import ObjectLostError

        from ray_tpu._private import flight_recorder

        flight_recorder.record("object.loss", len(obj_hexes))
        for obj_hex in obj_hexes:
            try:
                oid = ObjectID(bytes.fromhex(obj_hex))
            except (ValueError, TypeError):
                continue
            with self.store._lock:
                entry = self.store._entries.get(oid)
                remote = (entry is not None and entry.sealed
                          and isinstance(entry.value, RemoteBlob))
            if not remote or not self.store.mark_lost(oid):
                continue
            with self._locations_lock:
                self._object_locations.pop(oid, None)
            try:
                if not self.recovery.recover(oid):
                    self.store.put_error(oid, ObjectLostError(
                        ObjectRef(oid, _register=False),
                        f"object {oid.hex()} lost its last holder "
                        f"and has no lineage"))
            except Exception:  # noqa: BLE001 — one object must not strand
                logger.exception("failed to rebuild lost object %s",
                                 oid.hex())

    # ----------------------------------------------------------------- tasks

    _ARG_PIN_GRACE_S = 10.0

    def _pin_nested_arg_refs(self, args, kwargs) -> None:
        """Hold handles to refs NESTED in submitted args for a grace
        period. Nested refs aren't resolved by the submitter — the
        callee registers as a borrower — but that registration is
        asynchronous; without this pin, a driver that drops its own
        handle right after submit can free the object before the
        borrow lands (reference: the owner keeps in-flight task args
        reachable while the borrower list is being established,
        reference_count.h:61).

        This walk covers plain list/tuple/dict shapes at SUBMIT time;
        refs inside custom objects are caught later, completely, by the
        pickle-time collector in _convert_remote_args (until that
        serialization happens, the queued args tuple itself keeps every
        nested ObjectRef Python object — and hence its registered
        refcount — alive)."""
        refs: list = []

        def walk(v, depth=0):
            if isinstance(v, ObjectRef):
                refs.append(v)
            elif depth < 8 and type(v) in (list, tuple):
                for x in v:
                    walk(x, depth + 1)
            elif depth < 8 and type(v) is dict:
                for x in v.values():
                    walk(x, depth + 1)

        for a in args:
            walk(a, 1)  # TOP-LEVEL refs resolve before execution
        for v in kwargs.values():
            walk(v, 1)
        if refs:
            self._arg_pin_pen.append(
                (time.monotonic() + self._ARG_PIN_GRACE_S, refs))

    def _sweep_arg_pins(self) -> None:
        now = time.monotonic()
        while self._arg_pin_pen:
            deadline, _ = self._arg_pin_pen[0]
            if deadline > now:
                break
            try:
                self._arg_pin_pen.popleft()
            except IndexError:
                break

    def _arg_pin_sweeper(self) -> None:
        from ray_tpu._private.same_host import pin_ttl_s

        while not self._watcher_stop.wait(1.0):
            self._sweep_arg_pins()
            # Export map leases: liveness-gated TTL expiry, so a
            # SIGKILLed daemon cannot pin driver shared memory forever.
            now = time.monotonic()
            if now - self._lease_sweep_at >= 5.0:
                self._lease_sweep_at = now
                try:
                    self._export_leases.sweep(pin_ttl_s(),
                                              self._probe_peer)
                except Exception:  # noqa: BLE001 — sweep is best-effort
                    pass
                # Crashed co-hosted daemons' native arena segments
                # have no surviving unlinker; the driver reaps them
                # too (same_host.sweep_orphan_shm).
                try:
                    from ray_tpu._private.same_host import (
                        sweep_orphan_shm,
                    )

                    sweep_orphan_shm()
                except Exception:  # noqa: BLE001 — sweep is best-effort
                    pass
                # Same for SIGKILLed co-hosted owners' per-pid spill
                # directories (spill_manager.sweep_orphan_spill_dirs).
                try:
                    from ray_tpu._private import (
                        spill_manager as spill_mod,
                    )

                    if spill_mod.SPILL_ON:
                        spill_mod.sweep_orphan_spill_dirs()
                except Exception:  # noqa: BLE001 — sweep is best-effort
                    pass

    @staticmethod
    def _probe_peer(addr: str) -> bool:
        from ray_tpu._private.rpc import RpcClient

        probe = RpcClient(addr, timeout_s=2.0, connect_timeout_s=1.0)
        try:
            return probe.call("ping") == "pong"
        finally:
            probe.close()

    # ------------------------------------------------- overload control

    @staticmethod
    def _absolute_deadline(deadline_s: float | None) -> float | None:
        """now + budget, falling back to task_default_deadline_s."""
        if deadline_s is None:
            default = float(GLOBAL_CONFIG.task_default_deadline_s or 0)
            if default <= 0:
                return None
            deadline_s = default
        return time.time() + float(deadline_s)

    def _seal_deadline(self, spec_or_rec, stage: str) -> None:
        """Seal TaskTimeoutError onto a task whose end-to-end budget
        died at ``stage`` (shared by the ring flush, the dispatcher's
        queued/claim expiry hook, and the execute paths). The FAILED
        event records the stage so timeline() shows where the budget
        died."""
        err = TaskTimeoutError(
            getattr(spec_or_rec, "name", ""), stage,
            getattr(spec_or_rec, "deadline", 0.0) or 0.0)
        for rid in spec_or_rec.return_ids:
            self.store.put_error(rid, err)
        with self._fault_lock:
            self._task_timeouts += 1
        self.gcs.record_task_event(TaskEvent(
            spec_or_rec.task_id, getattr(spec_or_rec, "name", ""),
            "FAILED", end_time=time.time(),
            error=f"deadline expired at stage {stage!r}"))

    def _seal_overloaded(self, spec_or_rec, reason: str) -> None:
        """Shed a deadline-armed submit at admission: seal a retryable
        SystemOverloadedError instead of queueing unboundedly."""
        err = SystemOverloadedError(reason)
        for rid in spec_or_rec.return_ids:
            self.store.put_error(rid, err)
        with self._fault_lock:
            self._admission_shed += 1
        self.gcs.record_task_event(TaskEvent(
            spec_or_rec.task_id, getattr(spec_or_rec, "name", ""),
            "FAILED", end_time=time.time(), error=f"shed: {reason}"))

    def _admission_overload_reason(self) -> str | None:
        """Why admission should shed right now, or None. Queue-depth
        cap on the dispatcher backlog + host-memory watermark (both
        off by default; the watermark read is memoized)."""
        cap = int(GLOBAL_CONFIG.admission_max_queue_depth or 0)
        if cap > 0:
            depth = self.dispatcher.pending_count()
            if self._lanes is not None:
                depth += self._lanes.outstanding()
            if depth > cap:
                return (f"dispatcher backlog over "
                        f"admission_max_queue_depth={cap}")
        watermark = float(GLOBAL_CONFIG.admission_memory_watermark or 0)
        if watermark > 0:
            from ray_tpu._private import spill_manager as spill_mod
            from ray_tpu._private.memory_monitor import (
                memory_pressure_kind,
                memory_watermark_exceeded,
            )

            mgr = getattr(self.store, "_spill", None)
            if spill_mod.SPILL_ON and mgr is not None:
                # Two-axis split: STORE pressure is recoverable — kick
                # the spillers and admit (the job degrades to disk
                # instead of failing) unless the spill disk is full,
                # which sheds exactly like true HOST pressure.
                kind = memory_pressure_kind(watermark)
                if kind == "store":
                    if not mgr.backing_off():
                        mgr.request_spill()
                        if self._export_spill_mgr is not None:
                            self._export_spill_mgr.request_spill()
                        kind = None
                    else:
                        return ("store memory over admission_memory_"
                                f"watermark={watermark} and the spill "
                                "disk is full (backing off)")
                if kind == "host":
                    return (f"host memory over admission_memory_"
                            f"watermark={watermark}")
            elif memory_watermark_exceeded(watermark):
                # Spill tier disarmed: the PR-7 single-axis shed.
                return (f"host memory over admission_memory_watermark"
                        f"={watermark}")
        return None

    def submit_task(
        self,
        func,
        args: tuple,
        kwargs: dict,
        *,
        name: str,
        num_returns: int = 1,
        resources: dict[str, float],
        max_retries: int = 0,
        retry_exceptions: bool | list = False,
        scheduling_strategy: SchedulingStrategy | None = None,
        runtime_env: dict | None = None,
        deadline_s: float | None = None,
    ) -> list[ObjectRef]:
        """Reference: CoreWorker::SubmitTask (core_worker.cc:1998).

        With the submit pipeline armed (default), ``.remote()`` only
        allocates the task/return ids and pushes a record onto the
        submit ring — refs still come back synchronously, and
        pre-dispatch failures (runtime_env packaging, cancellation of
        a buffered submit) surface as errors sealed onto those refs.
        The ring's flush thread performs the batched record-keeping
        (_flush_submits).

        ``deadline_s`` arms the end-to-end deadline: an ABSOLUTE
        expiry (now + deadline_s) stamped on the spec and checked at
        every later stage; tasks without one inherit
        ``task_default_deadline_s`` (0 = no budget)."""
        deadline = self._absolute_deadline(deadline_s)
        ring = self._submit_ring
        if ring is None:
            return self._submit_task_inline(
                func, args, kwargs, name=name, num_returns=num_returns,
                resources=resources, max_retries=max_retries,
                retry_exceptions=retry_exceptions,
                scheduling_strategy=scheduling_strategy,
                runtime_env=runtime_env, deadline=deadline)
        rec = _SubmitRecord()
        rec.func = func
        rec.args = args
        rec.kwargs = kwargs
        rec.name = name
        rec.num_returns = num_returns
        rec.resources = resources
        rec.max_retries = max_retries
        rec.retry_exceptions = retry_exceptions
        rec.strategy = scheduling_strategy or SchedulingStrategy()
        rec.runtime_env = runtime_env
        rec.task_id = TaskID()
        rec.return_ids = [ObjectID() for _ in range(num_returns)]
        rec.submit_ts = 0.0
        rec.trace_ctx = None
        rec.cancelled = False
        rec.deadline = deadline
        rec.state = _SubmitRecord.BUFFERED
        if tracing.TRACE_ON or perf.PERF_ON:
            # Submit stamped at the TRUE .remote() call: the perf
            # plane's submit→dispatch histogram measures ring + queue
            # wait from here (always-on); the trace context (tracing
            # armed only) additionally links to the caller's open span
            # — the flush thread has no ambient span context, so
            # neither can be made there.
            now = time.time()
            rec.submit_ts = now
            if tracing.TRACE_ON:
                rec.trace_ctx = tracing.make_trace_context(anchor=now)
        # Register the refs directly against OUR counter: the generic
        # ObjectRef constructor re-resolves the global runtime per ref,
        # which is measurable at 100k submits.
        add_ref = self.reference_counter.add_ref
        refs = []
        for rid in rec.return_ids:
            ref = ObjectRef(rid, _register=False)
            add_ref(rid)
            ref._registered = True
            refs.append(ref)
        ring.push(rec)
        return refs

    def _submit_task_inline(
        self,
        func,
        args: tuple,
        kwargs: dict,
        *,
        name: str,
        num_returns: int = 1,
        resources: dict[str, float],
        max_retries: int = 0,
        retry_exceptions: bool | list = False,
        scheduling_strategy: SchedulingStrategy | None = None,
        runtime_env: dict | None = None,
        deadline: float | None = None,
    ) -> list[ObjectRef]:
        """The classic per-task submit path (submit_pipeline=0)."""
        if deadline is not None:
            # Fail-fast admission for deadline-armed inline submits:
            # the caller declared a latency budget, so reject instead
            # of queueing into a backlog that will eat it (the ring
            # path makes the same call per flush).
            reason = self._admission_overload_reason()
            if reason is not None:
                with self._fault_lock:
                    self._admission_shed += 1
                raise SystemOverloadedError(reason)
        task_id = TaskID()
        self._pin_nested_arg_refs(args, kwargs)
        return_ids = [ObjectID() for _ in range(num_returns)]
        strategy = scheduling_strategy or SchedulingStrategy()
        spec = TaskSpec(
            task_id=task_id, name=name, func=func, args=args, kwargs=kwargs,
            num_returns=num_returns, resources=resources,
            max_retries=max_retries, retry_exceptions=retry_exceptions,
            scheduling_strategy=strategy, return_ids=return_ids,
            runtime_env=self._package_runtime_env(runtime_env),
            deadline=deadline,
        )
        for rid in return_ids:
            self.store.create_pending(rid)
        refs = [ObjectRef(rid) for rid in return_ids]
        self.lineage.record(spec)
        submit_stages = {}
        if tracing.TRACE_ON or perf.PERF_ON:
            now = time.time()
            spec._submit_ts = now
            if tracing.TRACE_ON:
                # Root of this task's distributed trace: the context
                # rides the execute RPCs so daemon/worker spans link
                # back here.
                spec._trace_ctx = tracing.make_trace_context(anchor=now)
                if bool(GLOBAL_CONFIG.tracing_stage_timestamps):
                    submit_stages = {"submit": now}
        self.gcs.record_task_event(TaskEvent(task_id, name, "PENDING",
                                             stage_ts=submit_stages))
        deps = [a for a in args if isinstance(a, ObjectRef)] + [
            v for v in kwargs.values() if isinstance(v, ObjectRef)]

        if strategy.kind == "PLACEMENT_GROUP" and strategy.placement_group is not None:
            self._submit_pg_task(spec, deps, strategy)
        else:
            self.dispatcher.submit(spec, self._execute_task, deps)
        return refs

    def _seal_cancelled_submit(self, rec: _SubmitRecord) -> None:
        """A buffered (never-dispatched) submit was cancelled: seal the
        cancellation error onto its refs (put_error creates the store
        entries — they may not exist yet) and record the failure."""
        err = TaskCancelledError(rec.task_id)
        for rid in rec.return_ids:
            self.store.put_error(rid, err)
        self.gcs.record_task_event(TaskEvent(
            rec.task_id, rec.name, "FAILED", error="cancelled"))

    def _cancel_registered(self, object_id) -> None:
        """Cancel a task the dispatcher knows about (the classic
        cancel body, shared with the ring's post-flush cancel)."""
        spec = self.dispatcher.cancel_by_return_id(object_id)
        if spec is not None:
            err = TaskCancelledError(spec.task_id)
            for rid in spec.return_ids:
                self.store.put_error(rid, err)
            self.gcs.record_task_event(TaskEvent(
                spec.task_id, spec.name, "FAILED", error="cancelled"))

    def _flush_submits(self, ring: _SubmitRing,
                       records: "list[_SubmitRecord]") -> None:
        """Drain one submit-ring flush: build the TaskSpecs, then do
        ONE store.create_pending_batch lock pass, ONE
        lineage.record_many, ONE gcs.record_task_events PENDING batch
        and ONE dispatcher.submit_many wakeup for the whole flush —
        the per-task costs the inline path pays 100k times are paid
        once per flush here. ``ring`` is passed in (not read off self):
        shutdown detaches self._submit_ring before the final flush."""
        t_flush0 = time.perf_counter()
        live: list[_SubmitRecord] = []
        with ring._cond:
            for rec in records:
                if rec.cancelled:
                    # Sealed by ring.cancel() while BUFFERED: drop it.
                    for rid in rec.return_ids:
                        ring._by_rid.pop(rid, None)
                    continue
                rec.state = _SubmitRecord.DRAINING
                live.append(rec)
        if not live:
            return
        stamp_stages = tracing.TRACE_ON \
            and bool(GLOBAL_CONFIG.tracing_stage_timestamps)
        # Admission control at the flush boundary: over the queue-depth
        # cap / memory watermark, deadline-armed records are shed with
        # a retryable SystemOverloadedError (fail-fast — their budget
        # would die in the backlog anyway) while deadline-free records
        # wait here, which backpressures the ring and ultimately blocks
        # .remote() (bounded blocking, never loss).
        overload = self._admission_overload_reason()
        if overload is not None:
            armed = [rec for rec in live if rec.deadline is not None]
            if armed:
                for rec in armed:
                    self._seal_overloaded(rec, overload)
                shed_ids = {id(rec) for rec in armed}
                live = [rec for rec in live
                        if id(rec) not in shed_ids]
                with ring._cond:
                    for rec in armed:
                        rec.state = _SubmitRecord.SUBMITTED
                        for rid in rec.return_ids:
                            ring._by_rid.pop(rid, None)
            while live and self._admission_overload_reason() is not None:
                if ring._stop:
                    break  # shutdown flush must not wedge on overload
                time.sleep(0.02)
        now = time.time()
        specs: list[tuple[_SubmitRecord, TaskSpec, list]] = []
        events: list[TaskEvent] = []
        failed: list[tuple[_SubmitRecord, BaseException]] = []
        expired: list[_SubmitRecord] = []
        for rec in live:
            if rec.deadline is not None and now > rec.deadline:
                # The budget died while the record sat BUFFERED in the
                # ring (stage "submit"): seal the typed timeout without
                # ever creating scheduler-side state.
                expired.append(rec)
                continue
            try:
                # One scan serves both dep collection and the
                # container check gating the nested-ref grace pin
                # (top-level refs stay alive via spec.args itself;
                # refs inside custom objects are pinned later by the
                # pickle-time collector in _convert_remote_args).
                deps: list = []
                need_pin = False
                for a in rec.args:
                    if isinstance(a, ObjectRef):
                        deps.append(a)
                    elif type(a) in (list, tuple, dict):
                        need_pin = True
                for v in rec.kwargs.values():
                    if isinstance(v, ObjectRef):
                        deps.append(v)
                    elif type(v) in (list, tuple, dict):
                        need_pin = True
                if need_pin:
                    self._pin_nested_arg_refs(rec.args, rec.kwargs)
                spec = TaskSpec(
                    task_id=rec.task_id, name=rec.name, func=rec.func,
                    args=rec.args, kwargs=rec.kwargs,
                    num_returns=rec.num_returns, resources=rec.resources,
                    max_retries=rec.max_retries,
                    retry_exceptions=rec.retry_exceptions,
                    scheduling_strategy=rec.strategy,
                    return_ids=rec.return_ids,
                    runtime_env=self._package_runtime_env(rec.runtime_env),
                    deadline=rec.deadline,
                )
            except BaseException as exc:  # noqa: BLE001 — pre-dispatch
                failed.append((rec, exc))
                continue
            if rec.trace_ctx is not None:
                spec._trace_ctx = rec.trace_ctx
            if rec.submit_ts:
                # Perf plane: the submit→dispatch histogram anchors on
                # the true .remote() stamp, not the flush time.
                spec._submit_ts = rec.submit_ts
            events.append(TaskEvent(
                rec.task_id, rec.name, "PENDING",
                stage_ts={"submit": rec.submit_ts}
                if stamp_stages and rec.submit_ts else {}))
            specs.append((rec, spec, deps))
        # Batched record-keeping: one lock pass per subsystem. Every
        # pending entry exists before ANY task of this flush reaches
        # the dispatcher, so intra-flush dependencies gate correctly.
        self.store.create_pending_batch(
            [rid for _, spec, _ in specs for rid in spec.return_ids])
        self.lineage.record_many([spec for _, spec, _ in specs])
        if events:
            self.gcs.record_task_events(events)
        plain: list = []
        pg: list = []
        for rec, spec, deps in specs:
            strategy = spec.scheduling_strategy
            if strategy is not None and strategy.kind == "PLACEMENT_GROUP" \
                    and strategy.placement_group is not None:
                pg.append((spec, deps, strategy))
            else:
                plain.append((spec, self._execute_task, deps))
        if plain:
            self.dispatcher.submit_many(plain)
        for spec, deps, strategy in pg:
            self._submit_pg_task(spec, deps, strategy)
        for rec, exc in failed:
            # Pre-dispatch failure (e.g. runtime_env packaging): the
            # inline path would have raised out of .remote(); the
            # pipelined semantics surface it on the refs instead.
            for rid in rec.return_ids:
                self.store.put_error(rid, exc)
            self.gcs.record_task_event(TaskEvent(
                rec.task_id, rec.name, "FAILED", error=str(exc)))
        for rec in expired:
            self._seal_deadline(rec, "submit")
        # Hand the records over: cancels from here on ride the
        # dispatcher. A cancel that raced THIS flush (arrived while
        # DRAINING) is replayed against the dispatcher now.
        post_cancel: list[_SubmitRecord] = []
        with ring._cond:
            for rec in live:
                rec.state = _SubmitRecord.SUBMITTED
                for rid in rec.return_ids:
                    ring._by_rid.pop(rid, None)
                if rec.cancelled:
                    post_cancel.append(rec)
        for rec in post_cancel:
            if rec.return_ids:
                self._cancel_registered(rec.return_ids[0])
        self._flush_wall_us += int(
            (time.perf_counter() - t_flush0) * 1e6)

    # -------------------------------------------- columnar submit (ISSUE 15)

    def submit_columnar(self, template, args) -> "ObjectRef | None":
        """Columnar fast path for an eligible ``.remote()``: mint the
        ids, seed the ref, append ONE tuple to the lock-free buffer —
        no _SubmitRecord, no per-push lock, no notify during a burst.
        Returns None to send the caller down the classic ring path
        (ineligible args, lanes absent, tracing/speculation armed)."""
        lanes = self._lanes
        if lanes is None:
            return None
        ring = self._submit_ring
        if ring is None or ring._stop:
            return None
        # Per-task trace contexts / speculation tracking need real
        # TaskSpecs: the classic path owns those. (A disarmed watcher
        # object sticks around after configure_speculation toggles
        # off — SPEC_ON is the live gate.) One gate per branch.
        if tracing.TRACE_ON:
            return None
        if spec_mod.SPEC_ON and self._spec_watcher is not None:
            return None
        for a in args:
            if type(a) not in _COL_ARG_TYPES:
                return None
        buf = self._col_buf
        if len(buf) >= ring._capacity:
            ring.col_backpressure()
        task_id = TaskID()
        rid = ObjectID()
        # Index BEFORE the buffer append: a record popped by the flush
        # always finds its index entry (GIL program order).
        self._col_index[rid] = task_id
        buf.append((template, task_id, rid, args,
                    time.time() if perf.PERF_ON else 0.0))
        ref = ObjectRef(rid, _register=False)
        self.reference_counter.seed_ref(rid)
        ref._registered = True
        if ring._parked:
            ring.kick()
        return ref

    def _flush_columnar(self, ring: "_SubmitRing") -> None:
        """Drain one columnar flush: group the claimed records by
        template and do O(1) work per GROUP — one ColumnarGroup, one
        bulk rid->group index update, one lineage group record, one
        TaskEvent group record, one lane submission. The per-task
        TaskSpec/TaskEvent/ObjectEntry objects the classic flush
        builds are expanded lazily, only when something touches one."""
        buf = self._col_buf
        n = min(len(buf), ring._flush_max)
        if n <= 0:
            return
        t0 = time.perf_counter()
        records = []
        pop = buf.popleft
        for _ in range(n):
            try:
                records.append(pop())
            except IndexError:
                break
        # Admission control at the flush boundary: columnar records
        # are deadline-free by construction, so over the cap they WAIT
        # (which backpressures the buffer and ultimately .remote()) —
        # bounded blocking, never loss.
        while self._admission_overload_reason() is not None:
            if ring._stop:
                break
            time.sleep(0.02)
        index = self._col_index
        groups: list = []
        with self._col_lock:
            per: dict = {}
            for template, task_id, rid, args, ts in records:
                if index.get(rid) is not task_id:
                    continue  # cancelled while buffered (sealed there)
                cols = per.get(template)
                if cols is None:
                    cols = per[template] = ([], [], [], [])
                cols[0].append(task_id)
                cols[1].append(rid)
                cols[2].append(args)
                cols[3].append(ts)
            for template, cols in per.items():
                group = dispatch_lanes.ColumnarGroup(
                    template, cols[0], cols[1], cols[2], cols[3])
                index.update(dict.fromkeys(cols[1], group))
                groups.append(group)
        lanes = self._lanes
        for group in groups:
            # Lineage + PENDING events as per-flush group records,
            # registered BEFORE the lanes can dispatch any member.
            self.lineage.record_group(group)
            group.event_group = self.gcs.record_task_event_group(
                group.task_ids, group.template.name)
            lanes.submit_group(group)
        self._col_submits += len(records)
        self._col_flush_tasks += sum(len(g) for g in groups)
        self._flush_wall_us += int((time.perf_counter() - t0) * 1e6)
        with ring._cond:
            ring._cond.notify_all()  # unblock col_backpressure waiters

    def _cancel_columnar(self, object_id) -> bool:
        """Cancel routing for columnar ids. True => handled here (the
        error was sealed, or a racing cancel/seal already resolved the
        ref); False => not ours / already dispatched — the caller
        falls through to the dispatcher."""
        index = self._col_index
        st = index.get(object_id)
        if st is None:
            return False
        with self._col_lock:
            st = index.get(object_id)
            if st is None:
                return True  # raced a cancel or a terminal seal
            if st.__class__ is TaskID:
                # Still BUFFERED: the flush will skip the record (its
                # index entry no longer matches); seal here.
                index.pop(object_id, None)
                self._col_buffered_cancels += 1
                task_id, name = st, ""
            else:
                group = st
                if not self._lanes.cancel(object_id, group):
                    return False  # dispatched: best-effort no-op
                index.pop(object_id, None)
                idx = group.by_rid[object_id]
                task_id = group.task_ids[idx]
                name = group.template.name
        err = TaskCancelledError(task_id)
        self.store.put_error(object_id, err)
        self.gcs.record_task_event(TaskEvent(
            task_id, name, "FAILED", error="cancelled"))
        return True

    def _columnar_node_filter(self, node: NodeState) -> bool:
        # Dict membership under the GIL; lanes only dispatch to nodes
        # with a live daemon handle.
        return node.node_id in self._remote_nodes

    def _columnar_indexes_to_classic(self, group, idxs) -> None:
        """Hand columnar tasks to the classic dispatcher (starvation
        fallback, invisible requeues): expand the touched records into
        TaskSpecs, create their store pending entries (attach_future /
        state queries now see them there) and submit_many in one
        pass. The caller has already released any held claims."""
        index = self._col_index
        rids = [group.return_ids[gidx] for gidx in idxs]
        self.store.create_pending_batch(rids)
        items = []
        for gidx in idxs:
            index.pop(group.return_ids[gidx], None)
            items.append((group.spec_for(gidx), self._execute_task, []))
        if items:
            self.dispatcher.submit_many(items)
            self._lanes.task_done(len(items))

    def _columnar_starved(self, group, idxs) -> None:
        """Lane starvation fallback: no filtered (remote) node could
        admit this group for a while — the classic dispatcher owns the
        wait (it can also run the tasks locally)."""
        self._columnar_indexes_to_classic(group, idxs)

    def _columnar_local_fallback(self, group, sent, node) -> None:
        """The function can't cross a process boundary (unpicklable):
        run the slice in-thread via the classic single path, exactly
        like the classic batch runner's fallback."""
        resources = group.template.resources
        index = self._col_index
        for gidx in sent:
            rid = group.return_ids[gidx]
            index.pop(rid, None)
            self.store.create_pending(rid)
            spec = group.spec_for(gidx)
            try:
                self._execute_task(spec, node)
            finally:
                self.cluster.release(node.node_id, resources)
                self._lanes.task_done()

    def _run_columnar_slice(self, group, indexes, node,
                            n_over: int) -> None:
        """Runner-thread executor for one lane allocation: build the
        compact columnar batch RPC, seal streamed reply groups through
        the completion fast path, and route every non-happy reply
        through a lazily materialized TaskSpec on the classic
        machinery. Exactly-once discipline matches the classic batch
        runner: entries the daemon never announced requeue invisibly
        on a cut stream; announced ones fail as WorkerCrashedError
        (retried under the system-failure budget)."""
        from ray_tpu._private import serialization
        from ray_tpu._private.rpc import RpcError, RpcMethodError
        from ray_tpu.exceptions import WorkerCrashedError

        template = group.template
        resources = template.resources
        sent = list(indexes)
        with self._remote_nodes_lock:
            handle = self._remote_nodes.get(node.node_id)
        if handle is None:
            # Node dropped between plan and launch.
            self.cluster.release_many(node.node_id,
                                      [resources] * len(sent))
            self._columnar_indexes_to_classic(group, sent)
            return
        try:
            digest, func_blob = self._function_blob(template.func)
        except Exception:  # noqa: BLE001 — unpicklable: run locally
            self._columnar_local_fallback(group, sent, node)
            return
        with handle._digest_lock:
            known = digest in handle.known_digests
            handle.known_digests.add(digest)
        ser_raw = serialization.try_serialize_raw
        ser_framed = serialization.serialize_framed
        args_col = group.args_col
        rids = group.return_ids
        # Columnar wire: the blob encodes the ARGS TUPLE alone —
        # kwargs are empty by eligibility, so both ends skip the
        # (args, kwargs) nesting the classic frames carry.
        args_blobs = []
        return_keys = []
        for idx in sent:
            args = args_col[idx]
            blob = ser_raw(args)
            args_blobs.append(blob if blob is not None
                              else ser_framed(args))
            return_keys.append(rids[idx].binary())
        descriptor = ("col1", digest, None if known else func_blob,
                      args_blobs, return_keys, resources,
                      group.task_ids[sent[0]].hex())
        n = len(sent)
        done = bytearray(n)
        started: "set[int]" = set()
        cpu_only = {k: v for k, v in resources.items() if k == "CPU"}
        client_addr = self._client_server_addr() or None
        t_send = time.time()
        if perf.PERF_ON:
            ts_col = group.submit_ts
            if ts_col:
                perf.record_stage_many("submit_dispatch", [
                    max(0.0, t_send - ts_col[idx]) for idx in sent
                    if ts_col[idx]])

        def on_col(payload):
            start_local, items = payload
            self._seal_columnar_group(group, sent, done, start_local,
                                      items, node, handle, t_send)

        def on_results(pairs):
            # Classic replies: budget-spilled entries riding the
            # worker pipeline inside the columnar batch.
            for local_idx, reply in pairs:
                if done[local_idx]:
                    continue
                done[local_idx] = 1
                self._finish_columnar_classic(
                    group, sent[local_idx], node, handle, reply)

        def on_parked(local_idx):
            # Over-subscribed entry parked in daemon admission: give
            # its CPU back on the driver ledger until it resumes.
            if cpu_only:
                self.cluster.release(node.node_id, cpu_only)

        def on_resumed(local_idx):
            if cpu_only:
                self.cluster.force_acquire(node.node_id, cpu_only)

        transport_exc = None
        try:
            _, fused_stats = handle.execute_batch(
                descriptor, on_results, on_parked, on_resumed,
                client_addr, on_started=started.add, on_col=on_col)
            if fused_stats.get("fused") \
                    or fused_stats.get("fused_fallbacks"):
                with self._fault_lock:
                    if fused_stats.get("fused"):
                        self._fused_runs += 1
                        self._fused_tasks += int(fused_stats["fused"])
                    self._fused_fallbacks += int(
                        fused_stats.get("fused_fallbacks", 0))
        except (RpcError, RpcMethodError, OSError) as exc:
            transport_exc = exc
        except BaseException as exc:  # noqa: BLE001 — never strand
            # A reply-handler failure mid-stream must not strand the
            # slice's tasks (no seal = a get() hangs forever): treat
            # it like a cut stream — unfinished entries requeue/retry.
            logger.exception("columnar slice reply handling failed")
            transport_exc = exc
        missing = [i for i in range(n) if not done[i]]
        if not missing:
            return
        if transport_exc is not None and not handle.ping():
            self._drop_remote_node(node.node_id)
        for local_idx in missing:
            gidx = sent[local_idx]
            self.cluster.release(node.node_id, resources)
            requeues = group.requeues.get(gidx, 0)
            if local_idx not in started and requeues < 3:
                # Provably never ran (no started window covered it):
                # invisible requeue, no retry budget consumed.
                group.requeues[gidx] = requeues + 1
                with self._fault_lock:
                    self._fault_batch_requeues += 1
                self._columnar_indexes_to_classic(group, [gidx])
                continue
            spec = group.spec_for(gidx)
            self._col_index.pop(rids[gidx], None)
            self._lanes.task_done()
            self.store.create_pending(rids[gidx])
            err = WorkerCrashedError(
                f"node {node.node_id.hex()[:8]} lost task "
                f"{template.name} mid-batch: {transport_exc}")
            self._finish_task_failure(spec, err, t_send)

    def _seal_columnar_group(self, group, sent, done, start_local,
                             items, node, handle, t_send) -> None:
        """Completion FAST path: one store lock pass seals the whole
        reply group (batch listeners only — get-less tasks touch zero
        future machinery), one group-finished counter bump replaces
        per-task FINISHED events, one ledger pass releases the claims,
        and futures resolve only when any are actually attached."""
        from ray_tpu._private import serialization

        deser = serialization.deserialize_from_buffer
        rids = group.return_ids
        pairs = []
        classic = []
        for i, payload in enumerate(items):
            local_idx = start_local + i
            if done[local_idx]:
                continue
            done[local_idx] = 1
            if type(payload) is bytes:
                pairs.append((rids[sent[local_idx]],
                              deser(memoryview(payload))))
            else:
                classic.append((local_idx, payload))
        if pairs:
            self.store.put_group(pairs)
            if self._futures:
                for rid, _ in pairs:
                    self._resolve_futures(rid)
            event_group = group.event_group
            if event_group is not None:
                self.gcs.record_task_group_finished(event_group,
                                                    len(pairs))
            self.cluster.release_many(
                node.node_id, [group.template.resources] * len(pairs))
            self._lanes.task_done(len(pairs))
            index = self._col_index
            for rid, _ in pairs:
                index.pop(rid, None)
            if perf.PERF_ON:
                perf.record_stage_n("rpc_seal",
                                    max(0.0, time.time() - t_send),
                                    len(pairs))
        for local_idx, payload in classic:
            self._finish_columnar_classic(group, sent[local_idx],
                                          node, handle, payload)

    def _finish_columnar_classic(self, group, gidx, node, handle,
                                 reply) -> None:
        """A columnar entry left the happy path ('stored' results,
        errors, requeue shapes): expand the one touched record into a
        TaskSpec and give it to the classic machinery — retries,
        spillback, overload handling and events all behave exactly as
        on the classic batch path."""
        from ray_tpu._private import serialization

        spec = group.spec_for(gidx)
        rid = group.return_ids[gidx]
        self._col_index.pop(rid, None)
        self._lanes.task_done()
        self.store.create_pending(rid)
        resources = group.template.resources
        kind = reply[0]
        start = time.time()
        if kind == "ok":
            try:
                pairs: list = []
                self._collect_remote_results(
                    spec.return_ids, reply[1], node.node_id,
                    handle.address, pairs)
                if pairs:
                    self.store.put_batch(pairs)
                event_group = group.event_group
                if event_group is not None:
                    self.gcs.record_task_group_finished(event_group, 1)
            except BaseException as exc:  # noqa: BLE001
                self._finish_task_failure(spec, exc, start)
            self.cluster.release(node.node_id, resources)
            return
        if kind == "err":
            exc, tb = serialization.deserialize_from_buffer(
                memoryview(reply[1]))
            exc.__ray_tpu_remote_tb__ = tb
            self._finish_task_failure(spec, exc, start)
            self.cluster.release(node.node_id, resources)
            return
        if kind == "need_func":
            # Daemon restarted: re-ship via the single path (which
            # sends the function blob) on its own thread; the claim is
            # released when it completes.
            def redo(spec=spec):
                try:
                    self._execute_task(spec, node)
                finally:
                    self.cluster.release(node.node_id, resources)

            threading.Thread(target=redo, daemon=True,
                             name="ray_tpu-task-refunc").start()
            return
        # Requeue/terminal shapes release the claim first — their next
        # dispatch re-acquires through the classic admission.
        self.cluster.release(node.node_id, resources)
        if kind == "busy":
            self._spillback_requeue(spec, node)
        elif kind == "overloaded":
            self._handle_overloaded_reply(spec, node,
                                          "daemon admission shed")
        elif kind == "timeout":
            self._seal_deadline(
                spec, reply[1] if len(reply) > 1 and reply[1]
                else "admitted")
        elif kind == "cancelled":
            err = TaskCancelledError(spec.task_id)
            for r in spec.return_ids:
                self.store.put_error(r, err)
            self.gcs.record_task_event(TaskEvent(
                spec.task_id, spec.name, "FAILED", error="cancelled"))
        else:
            self._finish_task_failure(
                spec, RuntimeError(f"unknown columnar reply {kind!r}"),
                start)

    def _submit_pg_task(self, spec: TaskSpec, deps, strategy) -> None:
        """Route through the bundle ledger once the PG is committed."""
        pg = strategy.placement_group

        def run_when_ready(shadow=None):
            if shadow is not None:
                # The dispatcher stamped its claim time on the SHADOW
                # spec; fold it back onto the real one or PG tasks lose
                # their dispatch stage in merged traces.
                ts = getattr(shadow, "_stage_dispatch", None)
                if ts is not None:
                    spec._stage_dispatch = ts
            try:
                self.store.get(pg.ready_ref.id())  # wait for commit
                node_id = self.placement_groups.acquire_from_bundle(
                    pg.id, strategy.placement_group_bundle_index, spec.resources)
            except BaseException as exc:  # noqa: BLE001
                for rid in spec.return_ids:
                    self.store.put_error(rid, exc)
                return
            node = self.cluster.get_node(node_id)
            try:
                self._execute_task(spec, node, acquired=False)
            finally:
                self.placement_groups.release_to_bundle(
                    pg.id, strategy.placement_group_bundle_index, spec.resources)

        # PG tasks bypass cluster admission (resources come from the bundle),
        # but still respect dependency gating via the dispatcher.
        pg_spec = TaskSpec(
            task_id=spec.task_id, name=spec.name, func=spec.func, args=spec.args,
            kwargs=spec.kwargs, num_returns=spec.num_returns, resources={},
            return_ids=spec.return_ids, scheduling_strategy=SchedulingStrategy(),
            deadline=spec.deadline)
        pg_spec._original = spec
        # The shadow must carry the trace context too: the dispatcher
        # and event paths read the spec THEY were handed, and dropping
        # the context here made PG tasks vanish from merged traces.
        ctx = getattr(spec, "_trace_ctx", None)
        if ctx is not None:
            pg_spec._trace_ctx = ctx
        self.dispatcher.submit(pg_spec, lambda s, n: run_when_ready(s), deps)

    @staticmethod
    def _dispatch_stages(spec: TaskSpec) -> dict:
        """Stage stamps accumulated driver-side before execution (the
        scheduler's claim time); {} when tracing was off at claim."""
        ts = getattr(spec, "_stage_dispatch", None)
        return {"dispatch": ts} if ts is not None else {}

    def _ingest_reply_trace(self, spec: TaskSpec, handle, trace,
                            t_send: float, t_recv: float) -> None:
        """Fold a reply's piggybacked trace payload into the merged
        view: anchor the node's ClockSync on the exchange (half-RTT),
        offset-correct the daemon/worker stage stamps into driver
        clock, merge them into the task's event, and ingest the
        shipped spans."""
        if trace is None:
            return
        offset = 0.0
        now_remote = trace.get("now")
        if now_remote is not None:
            # Full NTP form: the daemon's admission stamp is its
            # request-receive time (t1), its "now" the reply-send time
            # (t2) — server processing time cancels out of the RTT.
            remote_recv = (trace.get("stages") or {}).get("admitted")
            offset = handle.clock.observe(t_send, t_recv,
                                          float(now_remote),
                                          remote_recv)
        stages = {}
        for key, value in (trace.get("stages") or {}).items():
            if key in tracing.STAGES and isinstance(value, (int, float)):
                stages[key] = float(value) + offset
        stages["rpc_sent"] = t_send
        stages["seal"] = time.time()
        # Causal floor: a sub-ms offset-estimation error must never
        # reorder stages across the clock boundary (admitted cannot
        # precede the rpc that carried it) — enforce happened-before
        # along the canonical chain.
        prev = None
        for key in tracing.STAGES:
            ts = stages.get(key)
            if ts is None:
                continue
            if prev is not None and ts < prev:
                stages[key] = ts = prev
            prev = ts
        self.gcs.merge_stage_ts(spec.task_id, stages)
        spans = trace.get("spans")
        if spans:
            tracing.ingest_spans(spans, offset)

    def _execute_task(self, spec: TaskSpec, node: NodeState, acquired: bool = True) -> None:
        """Reference: CoreWorker::ExecuteTask (core_worker.cc:2717)."""
        start = time.time()
        if spec.deadline is not None and start > spec.deadline:
            # Budget died between claim and launch (PG gating, requeue
            # waits, spillback backoff): seal, never execute dead work.
            self._seal_deadline(spec, "execute")
            return
        self.gcs.record_task_event(TaskEvent(
            spec.task_id, spec.name, "RUNNING", start_time=start,
            node_id=node.node_id.hex() if node else "",
            stage_ts=self._dispatch_stages(spec)
            if tracing.TRACE_ON else {}))
        RuntimeContext.set(
            task_id=spec.task_id, task_name=spec.name, job_id=self.job_id,
            node_id=node.node_id if node else None, actor_id=None)
        block_ctx = BlockedResourceContext(
            self.cluster, node.node_id, spec.resources) if (node and acquired) else None
        remote_handle = None
        if node is not None:
            with self._remote_nodes_lock:
                remote_handle = self._remote_nodes.get(node.node_id)
        watcher = self._spec_watcher
        tracked = spec_mod.SPEC_ON and watcher is not None \
            and watcher.track(spec, node)
        try:
            if remote_handle is not None:
                from ray_tpu._private.node_executor import (
                    NodeBusyError,
                    NodeOverloadedError,
                    TaskDeadlineExpired,
                    TaskSpeculationCancelled,
                )

                try:
                    ran_on_pool = self._try_execute_remote(
                        spec, node, remote_handle)
                except NodeBusyError:
                    self._spillback_requeue(spec, node)
                    return
                except TaskSpeculationCancelled:
                    # The daemon refused the lease: this member's token
                    # was loser-cancelled before its user function ran
                    # (a sibling copy already sealed). Nothing to seal.
                    if watcher is not None:
                        watcher.mark_cancelled(spec)
                    self.gcs.record_task_event(TaskEvent(
                        spec.task_id, spec.name, "FAILED",
                        start_time=start, end_time=time.time(),
                        error="speculation: cancelled before exec"))
                    return
                except TaskDeadlineExpired:
                    # The daemon found the budget dead at admission.
                    self._seal_deadline(spec, "admitted")
                    return
                except NodeOverloadedError as exc:
                    self._handle_overloaded_reply(spec, node, str(exc))
                    return
            elif self.worker_pool is not None:
                ran_on_pool = self._try_execute_on_pool(spec, node)
            else:
                ran_on_pool = False
            if not ran_on_pool:
                if spec.runtime_env:
                    _warn_runtime_env_ignored(
                        f"task {spec.name!r} runs in-thread")
                resolved_args, resolved_kwargs, _ = resolve_args(
                    spec.args, spec.kwargs, lambda ref: self.get([ref])[0])
                if block_ctx is not None:
                    block_ctx.__enter__()
                sample = perf.sample_start() if perf.PERF_ON else None
                try:
                    result = spec.func(*resolved_args, **resolved_kwargs)
                finally:
                    if block_ctx is not None:
                        block_ctx.__exit__(None, None, None)
                if sample is not None:
                    # In-thread execution: the driver is the worker, so
                    # attribution samples land directly.
                    s = perf.sample_end(spec.name, sample)
                    perf.record_task_resources(*s)
                    perf.record_stage("exec_local", s[1])
                self._store_task_result(spec, result, node)
            if tracked:
                # Completed wall sample for the speculation trigger's
                # per-function p99 (only successful completions feed
                # it — spillbacks/failures would skew the baseline).
                watcher.untrack(spec, completed=True)
            self.gcs.record_task_event(TaskEvent(
                spec.task_id, spec.name, "FINISHED", start_time=start,
                end_time=time.time(),
                node_id=node.node_id.hex() if node else ""))
        except BaseException as exc:  # noqa: BLE001 — becomes a TaskError ref
            self._finish_task_failure(spec, exc, start)
        finally:
            if tracked:
                watcher.untrack(spec)
            RuntimeContext.clear()

    def _finish_task_failure(self, spec: TaskSpec, exc: BaseException,
                             start: float) -> None:
        """Terminal failure handling shared by the single and batched
        execute paths: retry when policy allows, else seal the error."""
        watcher = self._spec_watcher
        if watcher is not None and watcher.absorb_failure(spec):
            # A speculation sibling already sealed the result (or is
            # still live and may yet): never seal an error over it —
            # speculation doubles as a hedge against node death.
            self.gcs.record_task_event(TaskEvent(
                spec.task_id, spec.name, "FAILED", start_time=start,
                end_time=time.time(),
                error=f"speculation: absorbed {exc!r}"))
            return
        if self._maybe_retry(spec, exc):
            return
        from ray_tpu.exceptions import ObjectLostError, WorkerCrashedError

        # ObjectLostError and WorkerCrashedError pass through unwrapped:
        # a task that failed because its input is unrecoverable (or its
        # worker died under it) should surface the system failure, not a
        # generic TaskError around it (reference: ray.exceptions raises
        # WorkerCrashedError directly).
        error = exc if isinstance(
            exc, (TaskError, TaskCancelledError, ObjectLostError,
                  WorkerCrashedError)) else \
            TaskError(exc,
                      getattr(exc, "__ray_tpu_remote_tb__", None)
                      or format_traceback(exc), spec.name)
        for rid in spec.return_ids:
            self.store.put_error(rid, error)
        self.gcs.record_task_event(TaskEvent(
            spec.task_id, spec.name, "FAILED", start_time=start,
            end_time=time.time(), error=repr(exc)))

    def _handle_overloaded_reply(self, spec: TaskSpec, node: NodeState,
                                 reason: str) -> None:
        """A daemon shed this task at admission (queue-depth cap /
        memory watermark / overload.saturate chaos). Deadline-armed
        tasks fail fast with the retryable SystemOverloadedError —
        their budget would die waiting anyway; deadline-free ones
        requeue like a busy spillback (bounded blocking, never loss)."""
        if spec.deadline is not None:
            self._seal_overloaded(
                spec, f"node {node.node_id.hex()[:8]} shed the task: "
                      f"{reason}")
            return
        with self._fault_lock:
            self._admission_shed += 1
        self._spillback_requeue(spec, node)

    def _spillback_requeue(self, spec: TaskSpec, node: NodeState) -> None:
        """Spillback (reference: the raylet redirects the lease):
        requeue avoiding this node; once every remote node has
        rejected, the avoid set resets so the task keeps probing as
        capacity frees up — after a growing delay, so saturated
        clusters are polled, not hammered with submit/RPC hot spins."""
        avoid = getattr(spec, "_avoid_nodes", set())
        avoid.add(node.node_id)
        delay = 0.0
        with self._remote_nodes_lock:
            if avoid >= set(self._remote_nodes):
                avoid = set()
                spills = getattr(spec, "_spill_rounds", 0) + 1
                spec._spill_rounds = spills
                delay = min(0.05 * (2 ** min(spills, 6)), 2.0)
        spec._avoid_nodes = avoid
        deps = [a for a in spec.args
                if isinstance(a, ObjectRef)] + [
            v for v in spec.kwargs.values()
            if isinstance(v, ObjectRef)]

        def requeue():
            self.dispatcher.submit(spec, self._execute_task, deps)

        if delay > 0:
            timer = threading.Timer(delay, requeue)
            timer.daemon = True
            timer.start()
        else:
            requeue()

    def _try_execute_on_pool(self, spec: TaskSpec, node=None) -> bool:
        """Run the task on a pool worker process behind the serialization
        boundary. Returns False (caller falls back to in-thread execution)
        when the function/args cannot cross it (unpicklable closures) or
        the task needs accelerator resources (pool workers are CPU
        processes; the driver's process owns the TPU-backed JAX).
        """
        from ray_tpu._private.worker_pool import _RemoteTaskError

        if any(k.startswith("TPU") for k in spec.resources):
            return False
        try:
            args_blob = self.worker_pool.marshal_args(
                spec.args, spec.kwargs, self._promote_to_shm)
            digest, func_blob = self._function_blob(spec.func)
        except Exception:  # noqa: BLE001 — not serializable: run in-thread
            return False
        # Registered for the task's lifetime: a nested get() from the
        # worker carries this token and releases the task's CPU here.
        token = spec.task_id.hex()
        if node is not None:
            with self._inflight_blocks_lock:
                self._inflight_blocks[token] = BlockedResourceContext(
                    self.cluster, node.node_id, spec.resources)
        # stages_out doubles as the perf-plane carrier even untraced:
        # the pool reply's resource sample rolls up on this driver.
        perf_stages: dict | None = {} if perf.PERF_ON else None
        try:
            results = self.worker_pool.run_task_blobs(
                digest, func_blob, args_blob, spec.num_returns,
                spec.return_ids, runtime_env=spec.runtime_env,
                task_token=token, stages_out=perf_stages)
        except _RemoteTaskError as rte:
            rte.cause.__ray_tpu_remote_tb__ = rte.remote_tb
            raise rte.cause from None
        finally:
            with self._inflight_blocks_lock:
                ctx = self._inflight_blocks.pop(token, None)
            if ctx is not None:
                # If the worker died/timed out mid-blocked-get, the CPU
                # release is still outstanding; undo it before the
                # dispatcher's own release double-counts availability.
                ctx.drain()
        if perf_stages:
            sample = perf_stages.get("perf")
            if sample is not None:
                try:
                    perf.record_task_resources(sample[0], sample[1],
                                               sample[2], sample[3])
                    perf.record_stage("exec_local", float(sample[1]))
                except (TypeError, IndexError):
                    pass
        watcher = self._spec_watcher
        if watcher is not None and not watcher.claim_win(spec):
            return True  # sibling sealed first: skip the loser's write
        for rid, value in results:
            self.store.put(rid, value)
            if node is not None:
                self._record_location(rid, node.node_id)
        return True

    def _convert_remote_args(self, args: tuple, kwargs: dict) -> bytes:
        """ObjectRef args become FetchRef location hints (the consuming
        node pulls peer-to-peer; the driver never relays the bytes) or
        inline values; everything else ships by value. Returns the
        framed args blob; raises when the args cannot cross a process
        boundary (reference: args are objects nodes fetch via the
        ownership directory, not payloads inlined per task)."""
        from ray_tpu._private import serialization
        from ray_tpu._private.node_executor import (
            FetchRef,
            RemoteBlob,
            _inline_reply_bytes,
        )
        from ray_tpu._private.object_store import _sizeof

        cache_key = None
        if len(args) <= 8 and len(kwargs) <= 8 \
                and all(_simple_arg(a, 1) for a in args) \
                and all(_simple_arg(v, 1) for v in kwargs.values()):
            cache_key = (args, tuple(sorted(kwargs.items())))
            with self._arg_blob_lock:
                blob = self._arg_blob_cache.get(cache_key)
                if blob is not None:
                    self._arg_blob_cache.move_to_end(cache_key)
                    self.arg_cache_hits += 1
                    return blob
            # Simple-arg tuples are exactly the raw-framing-eligible
            # shape: encode with the tag scheme instead of pickling
            # (the daemon/worker decode dispatches on the sentinel).
            raw = serialization.try_serialize_raw((args, kwargs))
            if raw is not None:
                with self._arg_blob_lock:
                    self._arg_blob_cache[cache_key] = raw
                    while len(self._arg_blob_cache) \
                            > _ARG_CACHE_MAX_ENTRIES:
                        self._arg_blob_cache.popitem(last=False)
                return raw

        inline_max = _inline_reply_bytes()

        def convert(a):
            if not isinstance(a, ObjectRef):
                return a
            id_bytes = a.id().binary()
            if self._export_store is not None \
                    and self._export_store.get(id_bytes) is not None:
                return FetchRef(id_bytes, self._export_addr)
            value = self.store.get(a.id())  # deps sealed at dispatch
            if isinstance(value, RemoteBlob):
                return FetchRef(id_bytes, value.addr)
            if self._export_store is not None \
                    and _sizeof(value) > inline_max:
                # Export once; every node pulls + caches it by id
                # instead of the driver re-shipping per task. Large
                # exports serialize STRAIGHT into named shared memory
                # (no transient heap copy): same-host daemons then map
                # the segment/arena zero-copy, and the chunked
                # cross-host path serves from the same mapping.
                header, buffers = serialization.serialize(value)
                size = serialization.framed_size(header, buffers)
                shm_blob = self._register_export_source(
                    id_bytes, header, buffers, size)
                if shm_blob is not None:
                    self._export_store.put(id_bytes, shm_blob)
                else:
                    blob = serialization.serialize_framed(value)
                    self._export_store.put(id_bytes, blob)
                return FetchRef(id_bytes, self._export_addr)
            return value

        # Refs nested in CUSTOM objects ship as pickled ObjectRefs the
        # callee re-registers as a borrower; collect them here (pickle
        # sees every ref, unlike any structural walk) and grace-pin so
        # a driver dropping its handle right after this serialization
        # can't free the object before that registration lands. The
        # collector wraps the WHOLE conversion: convert() itself
        # serializes large values into the export store, and refs
        # nested inside those must be pinned too.
        from ray_tpu._private.object_ref import collect_reduced_refs

        nested: list = []
        with collect_reduced_refs(nested):
            conv_args = tuple(convert(a) for a in args)
            conv_kwargs = {k: convert(v) for k, v in kwargs.items()}
            blob = serialization.serialize_framed((conv_args, conv_kwargs))
        if nested:
            self._arg_pin_pen.append(
                (time.monotonic() + self._ARG_PIN_GRACE_S, nested))
        if cache_key is not None and not nested \
                and len(blob) <= _ARG_CACHE_MAX_BLOB:
            with self._arg_blob_lock:
                self._arg_blob_cache[cache_key] = blob
                while len(self._arg_blob_cache) > _ARG_CACHE_MAX_ENTRIES:
                    self._arg_blob_cache.popitem(last=False)
        return blob

    def _seal_remote_results(self, return_ids, results, node_id,
                             address) -> None:
        """Seal an execute/actor-call reply: inline values locally,
        larger results as lazy RemoteBlob placeholders with a recorded
        location."""
        from ray_tpu._private import serialization
        from ray_tpu._private.node_executor import RemoteBlob

        for rid, packed in zip(return_ids, results):
            if packed[0] == "inline":
                self.store.put(rid, serialization.deserialize_from_buffer(
                    memoryview(packed[1])))
            elif packed[0] == "stored":
                # Result stays on the producing node; pull lazily.
                self.store.put(rid, RemoteBlob(
                    node_id.hex(), address, packed[1]))
                self._record_location(rid, node_id)
            else:  # ("err", blob): this return value failed to pickle
                exc, tb = serialization.deserialize_from_buffer(
                    memoryview(packed[1]))
                exc.__ray_tpu_remote_tb__ = tb
                raise exc

    def _try_execute_remote(self, spec: TaskSpec, node: NodeState,
                            handle) -> bool:
        """Dispatch to a worker-node daemon's executor (reference: lease
        request to a remote raylet + push to its worker pool,
        node_manager.cc:1714). Returns False when the function/args
        can't cross a process boundary (caller runs the task locally
        in-thread)."""
        from ray_tpu._private.rpc import RpcError
        from ray_tpu.exceptions import WorkerCrashedError

        try:
            digest, func_blob = self._function_blob(spec.func)
            args_blob = self._convert_remote_args(spec.args, spec.kwargs)
        except Exception:  # noqa: BLE001 — unpicklable: run locally
            return False
        return_keys = [rid.binary() for rid in spec.return_ids]
        # The task token keys the daemon's admission entry AND this
        # driver's block context: a nested get() from the daemon's pool
        # worker releases the task's CPU on BOTH ledgers while blocked.
        token = spec.task_id.hex()
        ctx = _RemoteBlockContext(self.cluster, node.node_id,
                                  spec.resources, handle, token)
        with self._inflight_blocks_lock:
            self._inflight_blocks[token] = ctx
        trace_ctx = getattr(spec, "_trace_ctx", None) \
            if tracing.TRACE_ON else None
        t_send = time.time()
        if perf.PERF_ON:
            claim = getattr(spec, "_stage_dispatch", None)
            if claim is not None:
                perf.record_stage("dispatch_rpc",
                                  max(0.0, t_send - claim))
        try:
            results, reply_trace = handle.execute(
                digest, func_blob, args_blob, spec.num_returns,
                return_keys, spec.runtime_env, spec.resources,
                task_token=token,
                client_addr=self._client_server_addr() or None,
                trace_ctx=trace_ctx, deadline=spec.deadline)
        except (RpcError, OSError) as exc:
            # Distinguish a dead node from a transient call failure: a
            # drop marks every object on the node lost and fires
            # lineage recovery — far too heavy for one reset socket.
            if not handle.ping():
                self._drop_remote_node(node.node_id)
            err = WorkerCrashedError(
                f"node {node.node_id.hex()[:8]} unreachable during "
                f"task {spec.name}: {exc}")
            raise err from exc
        finally:
            with self._inflight_blocks_lock:
                popped = self._inflight_blocks.pop(token, None)
            if popped is not None:
                popped.drain()
        watcher = self._spec_watcher
        if watcher is None or watcher.claim_win(spec):
            self._seal_remote_results(spec.return_ids, results,
                                      node.node_id, handle.address)
            if scheduler_mod.LOCALITY_ON:
                # The node now caches this task's pulled large args:
                # future tasks consuming them score it for locality.
                self._learn_arg_locality(spec, node)
        if perf.PERF_ON:
            # The remote round-trip envelope (rpc_sent → seal): the
            # daemon-side breakdown of this window lives in ITS
            # admit_worker/exec histograms.
            perf.record_stage("rpc_seal", time.time() - t_send)
        if reply_trace is not None:
            self._ingest_reply_trace(spec, handle, reply_trace, t_send,
                                     time.time())
        return True

    # ----------------------------------------------------- batched dispatch

    def _task_batch_key(self, spec: TaskSpec, node, run):
        """Dispatcher hook: tasks claimed for the same REMOTE node in
        one pass coalesce into a single execute_task_batch RPC. Local
        tasks, TPU tasks and custom run callables (placement-group
        wrappers) keep the A/B-measured thread-per-task path."""
        if node is None or run != self._execute_task:
            return None
        if any(k.startswith("TPU") for k in spec.resources):
            return None
        with self._remote_nodes_lock:
            if node.node_id not in self._remote_nodes:
                return None
        return node.node_id

    def _collect_remote_results(self, return_ids, results, node_id,
                                address, out_pairs) -> None:
        """Per-task reply descriptors -> (rid, value) seal pairs
        appended to ``out_pairs`` (the caller seals the whole
        completion group in one store.put_batch). Raises on an err
        descriptor — failing only ITS task."""
        from ray_tpu._private import serialization
        from ray_tpu._private.node_executor import RemoteBlob

        for rid, packed in zip(return_ids, results):
            if packed[0] == "inline":
                out_pairs.append((rid, serialization
                                  .deserialize_from_buffer(
                                      memoryview(packed[1]))))
            elif packed[0] == "stored":
                out_pairs.append((rid, RemoteBlob(
                    node_id.hex(), address, packed[1])))
                self._record_location(rid, node_id)
            else:  # ("err", blob): this return value failed to pickle
                exc, tb = serialization.deserialize_from_buffer(
                    memoryview(packed[1]))
                exc.__ray_tpu_remote_tb__ = tb
                raise exc

    def _run_task_batch(self, specs: list[TaskSpec], node: NodeState,
                        complete) -> None:
        """Batch runner handed to the dispatcher: ONE
        execute_task_batch RPC carries the whole run to ``node``;
        grouped completions seal in batches as they stream back, and
        each task's admission releases individually via ``complete``
        (no barrier on the slowest sibling)."""
        from ray_tpu._private import serialization
        from ray_tpu._private.rpc import RpcError, RpcMethodError
        from ray_tpu.exceptions import WorkerCrashedError

        with self._remote_nodes_lock:
            handle = self._remote_nodes.get(node.node_id)
        if handle is None:
            # Node dropped between claim and launch: the single path
            # owns the unreachable-node bookkeeping.
            for spec in specs:
                try:
                    self._execute_task(spec, node)
                finally:
                    complete(spec)
            return
        start = time.time()
        client_addr = self._client_server_addr() or None
        entries: list = []
        ctx_by_idx: dict[int, Any] = {}
        spec_by_idx: dict[int, TaskSpec] = {}
        fallback: list[TaskSpec] = []
        events = []
        for spec in specs:
            try:
                digest, func_blob = self._function_blob(spec.func)
                args_blob = self._convert_remote_args(spec.args,
                                                      spec.kwargs)
            except Exception:  # noqa: BLE001 — unpicklable: run locally
                fallback.append(spec)
                continue
            has_refs = any(isinstance(a, ObjectRef) for a in spec.args) \
                or any(isinstance(v, ObjectRef)
                       for v in spec.kwargs.values())
            token = spec.task_id.hex()
            with handle._digest_lock:
                known = digest in handle.known_digests
                # Optimistic: a daemon restart surfaces as a per-task
                # need_func reply, retried through the single path.
                handle.known_digests.add(digest)
            idx = len(entries)
            # Flags bit 0: args carry FetchRef placeholders; bit 2: the
            # dispatcher over-subscribed this claim past the node's
            # free slots (the daemon parks it in admission instead of
            # bouncing a busy spillback).
            entry = (
                digest, None if known else func_blob, args_blob,
                spec.num_returns,
                [rid.binary() for rid in spec.return_ids],
                spec.runtime_env, spec.resources, token,
                (1 if has_refs else 0)
                | (2 if getattr(spec, "_overcommit", False) else 0))
            trace_ctx = getattr(spec, "_trace_ctx", None) \
                if tracing.TRACE_ON else None
            if trace_ctx is not None or spec.deadline is not None:
                # Optional 10th/11th elements: trace context and the
                # absolute deadline — absent on both counts keeps the
                # plain wire shape byte-identical.
                entry = entry + (trace_ctx,)
            if spec.deadline is not None:
                entry = entry + (spec.deadline,)
            entries.append(entry)
            spec_by_idx[idx] = spec
            if spec_mod.SPEC_ON and self._spec_watcher is not None:
                self._spec_watcher.track(spec, node)
            ctx = _RemoteBlockContext(self.cluster, node.node_id,
                                      spec.resources, handle, token)
            ctx_by_idx[idx] = ctx
            with self._inflight_blocks_lock:
                self._inflight_blocks[token] = ctx
            events.append(TaskEvent(
                spec.task_id, spec.name, "RUNNING", start_time=start,
                node_id=node.node_id.hex(),
                stage_ts=self._dispatch_stages(spec)
                if trace_ctx is not None else {}))
        self.gcs.record_task_events(events)

        complete_many = getattr(complete, "many", None)

        def finish_idx(idx: int, defer: "list | None" = None) -> None:
            spec = spec_by_idx.pop(idx, None)
            if spec is None:
                return
            if self._spec_watcher is not None:
                self._spec_watcher.untrack(spec)
            ctx = ctx_by_idx.pop(idx, None)
            if ctx is not None:
                with self._inflight_blocks_lock:
                    self._inflight_blocks.pop(spec.task_id.hex(), None)
                ctx.drain()
            if defer is not None:
                # Group path: the caller releases the whole group's
                # claims in one ledger pass (complete_many).
                defer.append(spec)
            else:
                complete(spec)

        def on_results(group) -> None:
            pairs: list = []
            done_events = []
            deferred: "list | None" = [] if complete_many is not None \
                else None
            end = time.time()
            for idx, reply in group:
                spec = spec_by_idx.get(idx)
                if spec is None:
                    continue  # duplicate reply
                if perf.PERF_ON and reply[0] in ("ok", "err"):
                    # rpc_sent→seal per task (the streamed group's
                    # arrival is each member's seal moment).
                    perf.record_stage("rpc_seal", max(0.0, end - t_send))
                if reply[0] == "ok":
                    watcher = self._spec_watcher
                    if watcher is not None \
                            and not watcher.claim_win(spec):
                        # Speculation loser: sibling sealed first —
                        # skip the write, just release the claim.
                        finish_idx(idx, deferred)
                        continue
                    try:
                        self._collect_remote_results(
                            spec.return_ids, reply[1], node.node_id,
                            handle.address, pairs)
                        if watcher is not None:
                            watcher.untrack(spec, completed=True)
                        if scheduler_mod.LOCALITY_ON:
                            self._learn_arg_locality(spec, node)
                        done_events.append(TaskEvent(
                            spec.task_id, spec.name, "FINISHED",
                            start_time=start, end_time=end,
                            node_id=node.node_id.hex()))
                        if len(reply) > 2 and reply[2] is not None:
                            # Piggybacked trace payload: daemon/worker
                            # stage stamps + spans, offset-corrected
                            # against this exchange.
                            self._ingest_reply_trace(
                                spec, handle, reply[2], t_send, end)
                    except BaseException as exc:  # noqa: BLE001
                        self._finish_task_failure(spec, exc, start)
                    finish_idx(idx, deferred)
                elif reply[0] == "err":
                    exc, tb = serialization.deserialize_from_buffer(
                        memoryview(reply[1]))
                    exc.__ray_tpu_remote_tb__ = tb
                    self._finish_task_failure(spec, exc, start)
                    finish_idx(idx, deferred)
                elif reply[0] == "busy":
                    finish_idx(idx, deferred)
                    self._spillback_requeue(spec, node)
                elif reply[0] == "timeout":
                    # Daemon-side deadline expiry at admission or on
                    # the worker pipe (the reply names no stage; the
                    # error does).
                    self._seal_deadline(
                        spec, reply[1] if len(reply) > 1 and reply[1]
                        else "admitted")
                    finish_idx(idx, deferred)
                elif reply[0] == "overloaded":
                    finish_idx(idx, deferred)
                    self._handle_overloaded_reply(
                        spec, node, "daemon admission shed")
                elif reply[0] == "cancelled":
                    # Loser-cancelled before exec (speculation): the
                    # sibling's seal already carries the result.
                    if self._spec_watcher is not None:
                        self._spec_watcher.mark_cancelled(spec)
                    finish_idx(idx, deferred)
                else:  # ("need_func", _): single path re-ships the blob
                    def redo(spec=spec):
                        try:
                            self._execute_task(spec, node)
                        finally:
                            complete(spec)

                    spec_by_idx.pop(idx, None)
                    ctx = ctx_by_idx.pop(idx, None)
                    if ctx is not None:
                        with self._inflight_blocks_lock:
                            self._inflight_blocks.pop(
                                spec.task_id.hex(), None)
                        ctx.drain()
                    threading.Thread(target=redo, daemon=True,
                                     name="ray_tpu-task-refunc").start()
            if pairs:
                self.store.put_batch(pairs)
            if done_events:
                self.gcs.record_task_events(done_events)
            if deferred:
                # One ledger pass + one wakeup for the whole group's
                # claim releases (after the seal, so pending_count
                # never undercounts sealed-but-running work).
                complete_many(deferred)

        def on_parked(idx: int) -> None:
            # The daemon queued this task's frame behind a blocked
            # lease head: it holds admission without running — release
            # its CPU on the driver ledger until it actually starts.
            ctx = ctx_by_idx.get(idx)
            if ctx is not None:
                ctx.block()

        def on_resumed(idx: int) -> None:
            ctx = ctx_by_idx.get(idx)
            if ctx is not None:
                ctx.unblock(force=True)

        # Entries the daemon marked maybe-started (their frame reached
        # a worker before the stream cut): on node death these retry
        # under the system-failure budget; everything else provably
        # never ran and requeues invisibly.
        started_idx: set[int] = set()

        transport_exc: BaseException | None = None
        t_send = time.time()  # rpc_sent stamp + the ClockSync anchor
        if perf.PERF_ON:
            for spec in spec_by_idx.values():
                claim = getattr(spec, "_stage_dispatch", None)
                if claim is not None:
                    perf.record_stage("dispatch_rpc",
                                      max(0.0, t_send - claim))
        if entries:
            try:
                _, fused_stats = handle.execute_batch(
                    entries, on_results, on_parked, on_resumed,
                    client_addr, on_started=started_idx.add)
                if fused_stats.get("fused") \
                        or fused_stats.get("fused_fallbacks"):
                    with self._fault_lock:
                        if fused_stats.get("fused"):
                            self._fused_runs += 1
                            self._fused_tasks += int(
                                fused_stats["fused"])
                        self._fused_fallbacks += int(
                            fused_stats.get("fused_fallbacks", 0))
            except (RpcError, RpcMethodError, OSError) as exc:
                transport_exc = exc
        if spec_by_idx:
            # Stream cut (or daemon replied short): maybe-started
            # leftovers are in the same in-flight-loss state as a
            # failed single RPC; unstarted ones requeue invisibly (no
            # retry budget consumed — mirroring the daemon-internal
            # per-worker crash semantics one level up). A bounded
            # invisible-requeue count per spec stops a flapping daemon
            # from cycling a task forever without consuming budget.
            if transport_exc is not None and not handle.ping():
                self._drop_remote_node(node.node_id)
            for idx in list(spec_by_idx):
                spec = spec_by_idx.get(idx)
                if spec is None:
                    continue
                invisible = getattr(spec, "_invisible_requeues", 0)
                if idx not in started_idx and invisible < 3:
                    spec._invisible_requeues = invisible + 1
                    with self._fault_lock:
                        self._fault_batch_requeues += 1
                    finish_idx(idx)  # releases claim + block context
                    deps = [a for a in spec.args
                            if isinstance(a, ObjectRef)] + [
                        v for v in spec.kwargs.values()
                        if isinstance(v, ObjectRef)]
                    self.dispatcher.submit(spec, self._execute_task,
                                           deps)
                    continue
                err = WorkerCrashedError(
                    f"node {node.node_id.hex()[:8]} lost task "
                    f"{spec.name} mid-batch: {transport_exc}")
                self._finish_task_failure(spec, err, start)
                finish_idx(idx)
        for spec in fallback:
            try:
                self._execute_task(spec, node)
            finally:
                complete(spec)

    def ensure_client_server(self) -> None:
        """Start the client server on first need (idempotent)."""
        if self.worker_client_server is not None:
            return
        from ray_tpu.util.client import ClientServer

        host = "0.0.0.0" if self.gcs_client is not None else "127.0.0.1"
        self.worker_client_server = ClientServer(host=host, port=0).start()
        # Worker processes spawned after this inherit it via os.environ.
        os.environ["RAY_TPU_DRIVER_CLIENT_ADDR"] = \
            f"127.0.0.1:{self.worker_client_server.port}"

    def _package_runtime_env(self, renv: dict | None) -> dict | None:
        """Turn local working_dir / py_modules directories into content-
        hashed packages served from the export store, so remote nodes
        can fetch + cache them (reference:
        _private/runtime_env/packaging.py). Local-only runtimes (no
        export server) keep raw paths — every worker shares the
        filesystem there."""
        if not renv or self._obj_server is None:
            return renv
        from ray_tpu._private.runtime_env_packaging import (
            hash_directory,
            package_directory,
        )

        def pack(path, keep_name):
            if not (isinstance(path, str) and os.path.isdir(path)):
                return path
            key = os.path.abspath(path)
            # Re-hash per submit (cheap): edits to the directory must
            # ship fresh content, never a stale cached package.
            hash_hex = hash_directory(key)
            if self._pkg_hashes.get(key) != hash_hex:
                zipped_hash, blob = package_directory(key)
                self._export_store.put(bytes.fromhex(zipped_hash), blob)
                self._pkg_hashes[key] = zipped_hash
                hash_hex = zipped_hash
            member = os.path.basename(key.rstrip("/")) if keep_name \
                else None
            return {"__pkg__": [hash_hex, self._export_addr, member]}

        out = dict(renv)
        if "working_dir" in out:
            out["working_dir"] = pack(out["working_dir"], keep_name=False)
        if out.get("py_modules"):
            # py_modules stay importable by their directory NAME.
            out["py_modules"] = [pack(m, keep_name=True)
                                 for m in out["py_modules"]]
        if out.get("pip"):
            out["pip"] = self._package_pip_spec(out["pip"])
        return out

    def _package_pip_spec(self, spec):
        """Local wheel/requirement FILES in a pip spec become content-
        hashed export-store entries so remote daemons (no shared
        filesystem) can fetch them; requirement strings pass through
        (reference: runtime_env/pip.py + packaging.py URI scheme)."""
        from ray_tpu._private.runtime_env_pip import (
            _file_content_hash,
            normalize_pip_spec,
        )

        norm = normalize_pip_spec(spec)
        packages = []
        for entry in norm["packages"]:
            if os.path.isdir(entry):
                raise ValueError(
                    f"runtime_env pip entry {entry!r} is a directory; "
                    "build a wheel (source installs need a build "
                    "toolchain on every node)")
            if os.path.isfile(entry):
                # Content hash is memoized by (path, mtime, size); the
                # export-store put is skipped when this exact content
                # was already exported (repeat submits are free, like
                # the working_dir path's _pkg_hashes memo).
                hash_hex = _file_content_hash(entry)
                if self._pkg_hashes.get(("pip", entry)) != hash_hex:
                    with open(entry, "rb") as f:
                        self._export_store.put(
                            bytes.fromhex(hash_hex), f.read())
                    self._pkg_hashes[("pip", entry)] = hash_hex
                packages.append({"__pip_file__": [
                    hash_hex, self._export_addr,
                    os.path.basename(entry)]})
            else:
                packages.append(entry)
        return {"packages": packages,
                "pip_install_options": norm["pip_install_options"]}

    def _worker_log_context(self, base: str) -> "str | None":
        """Owner attribution for tailed worker logs: map the log file's
        worker index → live pid → the actor record executing there
        (ActorRecord.pid), so interleaved actor output is labeled with
        the actor id rather than an anonymous worker name."""
        pool = self.worker_pool
        if pool is None or not base.startswith("worker-w"):
            return None
        try:
            index = int(base[len("worker-w"):])
        except ValueError:
            return None
        pids = []
        with pool._index_lock:
            for w in pool._all_workers:
                if w.index == index:
                    pids.append(w.proc.pid)
        if not pids:
            # Process actors own dedicated workers outside the shared
            # pool (ProcessActor -> PoolWorker(-1)).
            for actor in list(self._actors.values()):
                w = getattr(actor, "_worker", None)
                if w is not None and getattr(w, "index", None) == index:
                    pids.append(w.proc.pid)
        if len(pids) != 1:
            return None  # unknown or ambiguous: keep the plain prefix
        for rec in self.gcs.list_actors():
            if rec.pid == pids[0] and rec.state == "ALIVE":
                return f"actor={rec.actor_id.hex()[:8]}"
        return None

    def lookup_block_context(self, token: str):
        """Block context of an in-flight pool task (client server calls
        this when a nested get carries the task's token)."""
        with self._inflight_blocks_lock:
            return self._inflight_blocks.get(token)

    # ------------------------------------------- locality-aware placement

    def _arg_bytes(self, object_id: ObjectID) -> "tuple[int, str | None]":
        """(resident bytes, primary holder hex) of a sealed argument:
        RemoteBlob placeholders report the producing node and true
        blob size; driver-exported args their export-store size (no
        single holder — pullers accrue via the learned map)."""
        from ray_tpu._private.node_executor import RemoteBlob

        with self.store._lock:
            entry = self.store._entries.get(object_id)
            if entry is None or not entry.sealed \
                    or entry.error is not None:
                return 0, None
            value = entry.value
            size = entry.size_bytes
        if isinstance(value, RemoteBlob):
            return int(value.size), value.node_hex
        if self._export_store is not None:
            exported = self._export_store.size(object_id.binary())
            if exported:
                return int(exported), None
        return int(size), None

    def _locality_for_spec(self, spec: TaskSpec) -> dict | None:
        """Dispatcher locality hook: {node hex -> resident bytes of
        this task's large args}. Sources, byte-weighted per arg at or
        above locality_min_arg_kb: the primary holder recorded by the
        owner-side object directory (stored results), the learned
        residency map (nodes that already pulled+cached the arg), and
        the head ObjectDirectory's multi-holder view."""
        min_bytes = self._locality_min_bytes
        if min_bytes <= 0:
            return None
        refs = [a for a in spec.args if isinstance(a, ObjectRef)]
        refs += [v for v in spec.kwargs.values()
                 if isinstance(v, ObjectRef)]
        if not refs:
            return None
        out: dict[str, float] = {}
        holder_cache = self._holder_cache
        spilled = self._spilled_holders
        for ref in refs:
            oid = ref.id()
            size, primary = self._arg_bytes(oid)
            if size < min_bytes:
                continue
            holders: set[str] = set()
            if primary:
                holders.add(primary)
            with self._arg_locality_lock:
                learned = self._arg_locality.get(oid)
                if learned:
                    holders |= learned
            extra = holder_cache.get(oid.hex())
            if extra:
                holders.update(extra)
            # Spill-aware discount: a holder whose copy currently
            # lives on its disk tier must pay a restore before serving
            # — it gets no free byte credit over pulling from memory
            # elsewhere (it still counts, at a fraction, since disk
            # beats a cross-node transfer).
            spilled_at = spilled.get(oid.hex())
            for node_hex in holders:
                credit = size * (0.25 if node_hex == spilled_at
                                 else 1.0)
                out[node_hex] = out.get(node_hex, 0.0) + credit
        return out or None

    def _learn_arg_locality(self, spec: TaskSpec,
                            node: NodeState) -> None:
        """A task consuming large args just completed on ``node``: the
        node's pull cache now holds those args, so score it for future
        placements (bounded LRU; the broadcast-arg pattern turns into
        locality hits from the second wave on)."""
        refs = [a for a in spec.args if isinstance(a, ObjectRef)]
        refs += [v for v in spec.kwargs.values()
                 if isinstance(v, ObjectRef)]
        if not refs or node is None:
            return
        min_bytes = self._locality_min_bytes
        eligible = [r.id() for r in refs
                    if self._arg_bytes(r.id())[0] >= min_bytes]
        if not eligible:
            return
        node_hex = node.node_id.hex()
        with self._arg_locality_lock:
            for oid in eligible:
                holders = self._arg_locality.get(oid)
                if holders is None:
                    holders = self._arg_locality[oid] = set()
                holders.add(node_hex)
                self._arg_locality.move_to_end(oid)
            while len(self._arg_locality) > 4096:
                self._arg_locality.popitem(last=False)

    def _sync_sched_feed(self) -> None:
        """Node-watcher beat: fold the GCS node-stats table (with
        receipt ages) into the scheduler's load view and refresh the
        ObjectDirectory holder cache — the two live inputs of
        locality-/load-aware pick_node."""
        if self.gcs_client is None:
            return
        try:
            table = self.gcs_client.call("node_stats",
                                         timeout_s=5.0) or {}
        except Exception:  # noqa: BLE001 — head unreachable: keep last
            return
        for hex_id, stats in table.items():
            if not isinstance(stats, dict):
                continue
            try:
                node_id = NodeID(bytes.fromhex(hex_id))
            except (ValueError, TypeError):
                continue
            hist = stats.get("stage_hist") or {}
            wait = 0.0
            for stage in ("admit_worker", "exec"):
                snap = hist.get(stage)
                if isinstance(snap, dict):
                    wait += perf.quantile(snap, 0.5)
            self.cluster.update_node_stats(
                node_id,
                running=float(stats.get("running", 0.0) or 0.0),
                depth=float(stats.get(
                    "depth", stats.get("running", 0.0)) or 0.0),
                wait_s=wait,
                age_s=float(stats.get("age_s", 0.0) or 0.0))
        try:
            locs = self.gcs_client.call("list_object_locations",
                                        None, True, timeout_s=5.0)
            if isinstance(locs, tuple) and len(locs) == 2:
                # Spill-aware view: holders whose only copy is on
                # their disk tier should not win byte-weighted
                # locality (a restore costs disk IO the byte credit
                # assumed was free).
                self._holder_cache, self._spilled_holders = locs
            elif isinstance(locs, dict):  # pre-spill-aware head
                self._holder_cache = locs
        except Exception:  # noqa: BLE001 — best-effort holder view
            pass

    def gcs_persist_stats(self) -> dict | None:
        """The head's durable-control-plane counters + live epoch
        (``/metrics`` ray_tpu_gcs_* families), cached a few seconds so
        scrapes don't turn into head RPC storms. None when there is no
        head to ask (local-only runtime)."""
        if self.gcs_client is None:
            return None
        now = time.monotonic()
        fetched_at, cached = self._gcs_persist_cache
        if cached is not None and now - fetched_at < 5.0:
            return cached
        try:
            stats = self.gcs_client.call("gcs_persist_stats",
                                         timeout_s=2.0)
        except Exception:  # noqa: BLE001 — head unreachable: last known
            return cached
        if isinstance(stats, dict):
            self._gcs_persist_cache = (now, stats)
            return stats
        return cached

    def gcs_shard_stats(self) -> list | None:
        """Per-shard stats rows from a sharded head (``/metrics``
        ray_tpu_gcs_shard{shard=,key=} family), same short cache as
        gcs_persist_stats. Empty list on an unsharded head; None when
        there is no head (or it predates sharding)."""
        if self.gcs_client is None:
            return None
        now = time.monotonic()
        fetched_at, cached = self._gcs_shard_cache
        if cached is not None and now - fetched_at < 5.0:
            return cached
        try:
            rows = self.gcs_client.call("gcs_shard_stats",
                                        timeout_s=2.0)
        except Exception:  # noqa: BLE001 — old/unreachable head
            return cached
        if isinstance(rows, list):
            self._gcs_shard_cache = (now, rows)
            return rows
        return cached

    def metrics_history(self, window_s: float | None = None,
                        node: str | None = None) -> dict | None:
        """Windowed per-node history from the head's ring store
        (cluster history plane): per-interval delta samples +
        rate-over-window per counter, ``degraded`` naming any stalled
        shard domains. Cached ~1s — ``top`` refreshing every second
        must not turn into a head RPC storm. None when there is no
        head (or it predates the history plane); a disarmed head
        answers ``armed=False``."""
        if self.gcs_client is None:
            return None
        now = time.monotonic()
        fetched_at, key, cached = self._history_cache
        if cached is not None and key == (window_s, node) \
                and now - fetched_at < 1.0:
            return cached
        try:
            hist = self.gcs_client.call(
                "metrics_history", window_s=window_s, node=node,
                timeout_s=2.0)
        except Exception:  # noqa: BLE001 — old/unreachable head
            return cached if key == (window_s, node) else None
        if isinstance(hist, dict):
            self._history_cache = (now, (window_s, node), hist)
            return hist
        return cached if key == (window_s, node) else None

    def cluster_health(self) -> dict | None:
        """The head watchdog's typed verdicts (active + recent fired
        ring with evidence windows). Same caching/None contract as
        metrics_history."""
        if self.gcs_client is None:
            return None
        now = time.monotonic()
        fetched_at, cached = self._health_cache
        if cached is not None and now - fetched_at < 1.0:
            return cached
        try:
            health = self.gcs_client.call("cluster_health",
                                          timeout_s=2.0)
        except Exception:  # noqa: BLE001 — old/unreachable head
            return cached
        if isinstance(health, dict):
            self._health_cache = (now, health)
            return health
        return cached

    def configure_speculation(self, enabled: bool) -> None:
        """Arm/disarm straggler speculation at runtime (benches A/B
        this; init honors the speculation_enabled knob). The watcher
        thread is created on first arm and survives disarms (SPEC_ON
        gates every site)."""
        GLOBAL_CONFIG.update({"speculation_enabled": bool(enabled)})
        (spec_mod.enable if enabled else spec_mod.disable)()
        if enabled and self._spec_watcher is None:
            self._spec_watcher = spec_mod.SpeculationWatcher(self)

    def _record_location(self, object_id: ObjectID, node_id: NodeID) -> None:
        """Owner-side object directory (reference:
        ownership_based_object_directory.h): which node holds the primary
        copy — the set of objects that die with that node."""
        node = self.cluster.get_node(node_id)
        if node is None or not node.alive:
            # A task that finished after its node was declared dead keeps
            # its driver-held result; recording the dead node would leave
            # a permanently stale entry.
            return
        with self._locations_lock:
            self._object_locations[object_id] = node_id
            self._loc_dirty_adds[object_id.hex()] = node_id.hex()
            self._loc_dirty_removes.discard(object_id.hex())

    def _on_gcs_reply_meta(self, meta: dict) -> None:
        """Reader-thread observer for the head's reply metadata: an
        epoch bump (head restart) schedules a full re-publish of
        everything this driver owns at the head — locations, actor
        registry, placement groups — under the new epoch."""
        epoch = meta.get("epoch") if isinstance(meta, dict) else None
        if not isinstance(epoch, int):
            return
        prior = self._gcs_epoch
        self._gcs_epoch = epoch
        if prior is not None and epoch != prior:
            from ray_tpu._private import flight_recorder

            flight_recorder.record("epoch.bump", prior, epoch)
            self._epoch_republish = True
            self._loc_keepalive = 0.0  # next flush full-republishes

    def _handle_stale_epoch(self, exc) -> bool:
        """True when ``exc`` is the typed stale-epoch fence: re-sync
        the epoch (the rejecting reply's error carries it) and
        schedule the full re-publish; the caller requeues its payload
        and the next flush lands under the current epoch."""
        from ray_tpu._private.gcs import StaleEpochError
        from ray_tpu._private.rpc import RpcMethodError

        cause = exc.cause if isinstance(exc, RpcMethodError) else exc
        if not isinstance(cause, StaleEpochError):
            return False
        from ray_tpu._private import flight_recorder

        flight_recorder.record("gcs.stale_epoch", cause.current_epoch)
        self._gcs_epoch = cause.current_epoch
        self._epoch_republish = True
        self._loc_keepalive = 0.0
        return True

    def _queue_actor_mirror(self, event) -> None:
        """Local pubsub 'actors' callback (any lifecycle transition —
        REGISTERED/ALIVE/RESTARTING/DEAD): queue the id for the
        watcher's batched publish. Must stay cheap — it runs inline
        with the transition."""
        try:
            _state, actor_id = event
        except (TypeError, ValueError):
            return
        with self._mirror_lock:
            self._actor_dirty.add(actor_id)

    def _flush_control_mirror(self) -> None:
        """Watcher-beat publish of the driver's control-plane state to
        the head: dirty actor records (full upserts — RESTARTING state
        and num_restarts included) and the placement-group snapshot on
        version bumps. After an epoch bump EVERYTHING re-publishes —
        the restarted head's snapshot may predate recent transitions,
        and a stale-epoch rejection proves the head never saw them."""
        if self.gcs_client is None:
            return
        if self._epoch_republish:
            self._epoch_republish = False
            with self._mirror_lock:
                self._actor_dirty.update(
                    r.actor_id for r in self.gcs.list_actors())
                self._pg_published_version = -1
        with self._mirror_lock:
            dirty, self._actor_dirty = self._actor_dirty, set()
        records = []
        for actor_id in dirty:
            record = self.gcs.get_actor(actor_id)
            if record is not None:
                records.append(self.gcs._actor_plain(record))
        if records:
            try:
                self.gcs_client.call(
                    "actor_update", records, epoch=self._gcs_epoch,
                    timeout_s=10.0)
            except Exception as exc:  # noqa: BLE001 — requeue, retry next beat
                self._handle_stale_epoch(exc)
                with self._mirror_lock:
                    self._actor_dirty.update(dirty)
        pg_version = getattr(self.placement_groups, "version", 0)
        if pg_version != self._pg_published_version:
            try:
                self.gcs_client.call(
                    "pg_update", self.job_id.hex(),
                    self.placement_groups.snapshot(),
                    epoch=self._gcs_epoch, timeout_s=10.0)
                self._pg_published_version = pg_version
            except Exception as exc:  # noqa: BLE001 — retry next beat
                self._handle_stale_epoch(exc)

    def _flush_object_locations(self) -> None:
        """Batched publish of location deltas to the head's object-
        location table; an empty update every 10s keeps the owner's
        entries leased while it lives."""
        if self.gcs_client is None or not self._export_addr:
            return
        with self._locations_lock:
            adds = list(self._loc_dirty_adds.items())
            removes = list(self._loc_dirty_removes)
            self._loc_dirty_adds.clear()
            self._loc_dirty_removes.clear()
            have_entries = bool(self._object_locations)
        now = time.monotonic()
        if not adds and not removes:
            if not have_entries or now - self._loc_keepalive < 10.0:
                return
            # Keepalive doubles as a FULL re-publish: a restarted head
            # (in-memory table) or a >TTL driver stall must not lose
            # the surviving entries forever.
            with self._locations_lock:
                adds = [(oid.hex(), nid.hex()) for oid, nid
                        in self._object_locations.items()]
        try:
            self.gcs_client.call("object_locations_update",
                                 self._export_addr, adds, removes,
                                 epoch=self._gcs_epoch)
            self._loc_keepalive = now
        except Exception as exc:  # noqa: BLE001 — head unreachable: requeue
            # Stale-epoch fence: the head restarted and this driver's
            # deltas were rejected typed so an old incarnation's view
            # can't corrupt the restored directory. Re-sync + requeue;
            # the next flush FULL-republishes under the new epoch.
            self._handle_stale_epoch(exc)
            with self._locations_lock:
                for obj_hex, node_hex in adds:
                    self._loc_dirty_adds.setdefault(obj_hex, node_hex)
                self._loc_dirty_removes.update(removes)

    def _forget_object(self, object_id: ObjectID) -> None:
        with self._locations_lock:
            node_id = self._object_locations.pop(object_id, None)
            if node_id is not None:
                self._loc_dirty_removes.add(object_id.hex())
                self._loc_dirty_adds.pop(object_id.hex(), None)
        if self._export_store is not None:
            self._export_store.free([object_id.binary()])
        if self._export_directory is not None:
            self._export_directory.drop([object_id.binary()])
        self._drop_export_source(object_id.binary())
        if node_id is not None:
            # Remote primary copy: tell the holder to drop it (owner-
            # driven GC — batched by the node watcher). Queue even when
            # the handle is transiently gone: the flush retains entries
            # until the node returns, else the blob leaks in its store.
            with self._remote_nodes_lock:
                ever_remote = node_id in self._remote_ever
            if ever_remote:
                with self._remote_free_lock:
                    self._remote_free_queue.append(
                        (node_id, object_id.binary()))
        self.lineage.forget([object_id])

    def _function_blob(self, func) -> tuple[str, bytes]:
        """Serialize a task function once per identity (reference:
        function_manager.py exports each function to the GCS KV once).
        Like the reference, closures are captured at first export."""
        import hashlib

        from ray_tpu._private import serialization

        try:
            cached = self._func_blobs.get(func)
        except TypeError:  # unhashable callable
            cached = None
        if cached is not None:
            return cached
        blob = serialization.dumps_function(func)
        entry = (hashlib.sha1(blob).hexdigest(), blob)
        try:
            self._func_blobs[func] = entry
        except TypeError:
            pass
        return entry

    def _promote_to_shm(self, ref: ObjectRef):
        """Object directory lookup-or-promote: make a driver-held object
        reachable by worker processes via a shared-memory segment.

        Serialized under a lock: two dispatcher threads promoting the
        same ref concurrently would otherwise race the arena's
        duplicate-id check and leak a pinned arena entry.
        """
        from ray_tpu._private.shm_store import ShmObjectWriter

        from ray_tpu._private import serialization

        with self._promote_lock:
            self._recent_promotes[ref.id()] = time.monotonic()
            desc = self.shm_directory.lookup(ref.id())
            if desc is not None:
                return desc
            value = self._materialize_value(
                ref.id(), self.store.get(ref.id()))  # deps sealed at dispatch
            header, buffers = serialization.serialize(value)
            size = serialization.framed_size(header, buffers)
            if (self.arena is not None and size <= int(
                    GLOBAL_CONFIG.object_arena_max_object_bytes)):
                # Arena-first: keyed by the object id, so repeated
                # promotes of the same object are one table hit, not a
                # new segment.
                adesc = ShmObjectWriter.put_arena_serialized(
                    self.arena, ref.id().binary(), header, buffers, size)
                if adesc is not None:
                    self.shm_directory.register_arena(ref.id(), adesc)
                    return adesc
            desc, seg = ShmObjectWriter.put_serialized(
                header, buffers, size)
            self.shm_directory.register(ref.id(), desc, seg)
            return desc

    def _maybe_retry(self, spec: TaskSpec, exc: BaseException) -> bool:
        """Owner-driven retry (reference: task_manager.h:195, max_task_retries
        common.proto:645). System failures (worker death) retry whenever
        retries remain; application errors only if retry_exceptions
        allows them."""
        from ray_tpu.exceptions import WorkerCrashedError

        # OOM kills by the memory monitor carry their own retry budget
        # (reference: OOM failures retry independently of
        # max_task_retries — the task did nothing wrong).
        oom_kill = (isinstance(exc, WorkerCrashedError)
                    and self.memory_monitor is not None
                    and getattr(exc, "worker_pid", None)
                    in self.memory_monitor.killed_pids)
        if oom_kill and spec.attempt + 1 >= int(
                GLOBAL_CONFIG.task_oom_retries):
            # Final OOM attempt: consume the attribution so a recycled
            # pid cannot reclassify a future unrelated crash.
            self.memory_monitor.consume_attribution(exc.worker_pid)
        retry_budget = max(spec.max_retries,
                           int(GLOBAL_CONFIG.task_oom_retries)
                           if oom_kill else spec.max_retries)
        if spec.attempt >= retry_budget:
            return False
        retry_ok = False
        if isinstance(exc, (ActorDiedError, WorkerCrashedError)):
            retry_ok = True
        elif spec.retry_exceptions is True:
            retry_ok = True
        elif isinstance(spec.retry_exceptions, (list, tuple)):
            retry_ok = any(isinstance(exc, t) for t in spec.retry_exceptions)
        if not retry_ok:
            return False
        spec.attempt += 1
        logger.info("Retrying task %s (attempt %d/%d) after %r",
                    spec.name, spec.attempt, spec.max_retries, exc)
        deps = [a for a in spec.args if isinstance(a, ObjectRef)] + [
            v for v in spec.kwargs.values() if isinstance(v, ObjectRef)]
        self.dispatcher.submit(spec, self._execute_task, deps)
        return True

    def _store_task_result(self, spec: TaskSpec, result: Any,
                           node: NodeState | None = None) -> None:
        watcher = self._spec_watcher
        if watcher is not None and not watcher.claim_win(spec):
            # Speculation first-seal-wins: a sibling already sealed —
            # never overwrite the winning value with a late loser's.
            return
        if spec.num_returns == 1:
            self.store.put(spec.return_ids[0], result)
        elif spec.num_returns == 0:
            pass
        else:
            if not isinstance(result, (tuple, list)) or len(result) != spec.num_returns:
                raise ValueError(
                    f"Task {spec.name} declared num_returns={spec.num_returns} but "
                    f"returned {type(result).__name__} of length "
                    f"{len(result) if isinstance(result, (tuple, list)) else 'n/a'}")
            for rid, value in zip(spec.return_ids, result):
                self.store.put(rid, value)
        if node is not None:
            for rid in spec.return_ids:
                self._record_location(rid, node.node_id)

    # ---------------------------------------------------------------- actors

    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        name: str | None = None,
        namespace: str | None = None,
        resources: dict[str, float],
        max_concurrency: int = 1,
        max_restarts: int = 0,
        max_pending_calls: int = -1,
        lifetime: str | None = None,
        scheduling_strategy: SchedulingStrategy | None = None,
        get_if_exists: bool = False,
        process: bool = False,
        runtime_env: dict | None = None,
        deadline_s: float | None = None,
    ) -> tuple[ActorID, ObjectRef]:
        """Reference: CoreWorker::CreateActor (core_worker.cc:2069) +
        GcsActorManager registration. ``deadline_s`` becomes the
        actor's default per-call end-to-end budget."""
        ns = namespace or self.namespace
        if name is not None and get_if_exists:
            existing = self.gcs.get_named_actor(name, ns)
            if existing is not None:
                ready = ObjectRef(ObjectID())
                self.store.put(ready.id(), None)
                return existing.actor_id, ready
        actor_id = ActorID()
        creation_rid = ObjectID()
        self.store.create_pending(creation_rid)
        creation_ref = ObjectRef(creation_rid)
        method_meta = {}
        for attr_name in dir(cls):
            attr = getattr(cls, attr_name, None)
            if callable(attr) and hasattr(attr, "__ray_tpu_num_returns__"):
                method_meta[attr_name] = {
                    "num_returns": attr.__ray_tpu_num_returns__}
        record = ActorRecord(
            actor_id=actor_id, name=name, namespace=ns,
            class_name=cls.__name__, max_restarts=max_restarts,
            method_meta=method_meta,
            default_deadline_s=float(deadline_s or 0.0))
        try:
            self.gcs.register_actor(record)
            # Publish synchronously at registration so an actor is
            # resolvable from other drivers the moment .remote()
            # returns (calls queue until it is alive; every failure
            # path below unpublishes).
            if name is not None:
                self._publish_named_actor(record)
        except ValueError:
            # Named-actor registration race: two concurrent get_if_exists
            # creators both passed the existence check; the loser joins
            # the winner's actor (reference: GcsActorManager resolves
            # RegisterActor name collisions the same way). Seal the
            # already-created pending ref so nothing waits on it forever.
            if name is not None and get_if_exists:
                existing = self.gcs.get_named_actor(name, ns)
                if existing is not None:
                    self.store.put(creation_rid, None)
                    return existing.actor_id, creation_ref
            raise

        strategy = scheduling_strategy or SchedulingStrategy()

        # Remote placement probe: an actor can only execute on a worker
        # daemon when its class and init args cross a process boundary.
        # Unserializable actors (closures over driver state) stay on the
        # driver host, as do zero-resource default-strategy actors
        # (cheap; keeping them local preserves thread-actor semantics).
        serializable = True
        with self._remote_nodes_lock:
            any_remote = bool(self._remote_nodes)
        if any_remote:
            from ray_tpu._private import serialization as _ser

            try:
                # _function_blob caches by identity, so RemoteActor's own
                # dumps_function of the same class is a cache hit.
                self._function_blob(cls)
                if args or kwargs:  # skip the probe for no-arg actors
                    probe_args = tuple(
                        None if isinstance(a, ObjectRef) else a
                        for a in args)
                    probe_kwargs = {
                        k: None if isinstance(v, ObjectRef) else v
                        for k, v in kwargs.items()}
                    _ser.serialize_framed((probe_args, probe_kwargs))
            except Exception:  # noqa: BLE001 — not remotable
                serializable = False

        def remote_exclude() -> set | None:
            """Nodes an actor must avoid: remote daemons when the actor
            cannot leave the driver process."""
            keep_local = (not serializable or (
                strategy.kind == "DEFAULT"
                and not any(resources.values())))
            if not keep_local:
                return None
            with self._remote_nodes_lock:
                return set(self._remote_nodes) or None

        def start_actor():
            # Lease actor resources for its lifetime.
            node_id = None
            pg_info = None
            try:
                if strategy.kind == "PLACEMENT_GROUP" and strategy.placement_group is not None:
                    pg = strategy.placement_group
                    self.store.get(pg.ready_ref.id())
                    node_id = self.placement_groups.acquire_from_bundle(
                        pg.id, strategy.placement_group_bundle_index, resources)
                    pg_info = (pg.id, strategy.placement_group_bundle_index)
                else:
                    deadline = time.monotonic() + float(
                        GLOBAL_CONFIG.actor_lease_timeout_s)
                    while node_id is None:
                        node = self.cluster.pick_node(
                            resources, strategy, exclude=remote_exclude())
                        if node is not None and self.cluster.try_acquire(
                                node.node_id, resources):
                            node_id = node.node_id
                            break
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                f"Could not lease resources {resources} "
                                f"for actor {cls.__name__} within "
                                f"{GLOBAL_CONFIG.actor_lease_timeout_s}s")
                        self.cluster.wait_for_change(0.05)
            except BaseException as exc:  # noqa: BLE001
                self.store.put_error(creation_rid, exc)
                self.gcs.update_actor_state(actor_id, "DEAD", repr(exc))
                if name is not None:
                    self._unpublish_named_actor(ns, name)
                return

            def on_death(aid, reason):
                self.gcs.update_actor_state(aid, "DEAD", reason)
                if name is not None:
                    self._unpublish_named_actor(ns, name)
                self._release_actor_lease(aid)

            def on_restart(aid):
                actor = self._actors.get(aid)
                rec = self.gcs.get_actor(aid)
                if actor is not None and rec is not None:
                    # Restarts may have RELOCATED the actor.
                    self._record_actor_placement(
                        rec, actor, getattr(actor, "node_id", None))
                self.gcs.update_actor_state(aid, "ALIVE")

            # Record the lease BEFORE constructing the actor: a remote
            # actor's creation thread may relocate (busy daemon) and
            # must find the current lease to release it.
            self._actor_leases[actor_id] = (node_id, resources, pg_info)
            remote_handle = None
            if node_id is not None and serializable:
                with self._remote_nodes_lock:
                    remote_handle = self._remote_nodes.get(node_id)
            if remote_handle is not None:
                from ray_tpu._private.remote_actor import RemoteActor

                # The actor executes ON the leased daemon node — its
                # process lives in that daemon's tree, so the lease and
                # the execution site agree (reference: the GCS actor
                # scheduler creates the actor on the node whose
                # resources it claimed, gcs_actor_scheduler.h).
                self.ensure_client_server()
                actor = RemoteActor(
                    actor_id, cls, args, kwargs, self,
                    node_id=node_id, handle=remote_handle,
                    resources=resources,
                    max_restarts=max_restarts,
                    max_pending_calls=max_pending_calls,
                    max_concurrency=max_concurrency,
                    creation_return_id=creation_rid, on_death=on_death,
                    on_restart=on_restart,
                    runtime_env=self._package_runtime_env(runtime_env))
            elif process:
                from ray_tpu._private.worker_pool import ProcessActor

                # The actor's process needs the nested-API endpoint in
                # its inherited env BEFORE it spawns.
                self.ensure_client_server()
                actor = ProcessActor(
                    actor_id, cls, args, kwargs, self,
                    max_restarts=max_restarts,
                    max_pending_calls=max_pending_calls,
                    max_concurrency=max_concurrency,
                    creation_return_id=creation_rid, on_death=on_death,
                    on_restart=on_restart,
                    runtime_env=self._package_runtime_env(runtime_env))
            else:
                if runtime_env:
                    _warn_runtime_env_ignored(
                        f"actor {cls.__name__} runs in-process "
                        "(pass process=True)")
                actor = LocalActor(
                    actor_id, cls, args, kwargs, self,
                    max_concurrency=max_concurrency, max_restarts=max_restarts,
                    max_pending_calls=max_pending_calls,
                    creation_return_id=creation_rid, on_death=on_death,
                    on_restart=on_restart)
            with self._actors_changed:
                self._actors[actor_id] = actor
                self._actors_changed.notify_all()
            record.handle = actor
            self._record_actor_placement(record, actor, node_id)
            self.gcs.update_actor_state(actor_id, "ALIVE")

        self._actor_create_pool.submit(start_actor)
        return actor_id, creation_ref

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args: tuple, kwargs: dict,
                          num_returns: int = 1,
                          deadline_s: float | None = None) -> list[ObjectRef]:
        """Reference: CoreWorker::SubmitActorTask (core_worker.cc:2304).

        All calls for one actor flow through a per-actor ordered submission
        queue so per-caller call order is preserved even across actor
        startup and ObjectRef-argument resolution (reference:
        transport/sequential_actor_submit_queue.h).

        ``deadline_s`` (or the actor's default, or
        task_default_deadline_s) arms an end-to-end budget: a call
        whose deadline dies queued seals TaskTimeoutError instead of
        executing."""
        return_ids = [ObjectID() for _ in range(max(1, num_returns))]
        self._pin_nested_arg_refs(args, kwargs)
        for rid in return_ids:
            self.store.create_pending(rid)
        refs = [ObjectRef(rid) for rid in return_ids]
        call = _ActorCall(method_name, args, kwargs, return_ids,
                          deadline=self._absolute_deadline(deadline_s))

        record = self.gcs.get_actor(actor_id)
        if record is None or (record.state == "DEAD" and actor_id not in self._actors):
            err = ActorDiedError(actor_id, (record.death_cause if record else None)
                                 or "actor not found")
            for rid in return_ids:
                self.store.put_error(rid, err)
            return refs
        self._actor_submit_queue(actor_id).put(call)
        return refs

    def _actor_submit_queue(self, actor_id: ActorID):
        """Lazily start the per-actor ordered submission worker."""
        import queue as queue_mod

        with self._futures_lock:
            entry = self._actor_queues.get(actor_id)
            if entry is not None:
                return entry
            submit_queue: queue_mod.Queue = queue_mod.Queue()
            self._actor_queues[actor_id] = submit_queue

        def drain():
            while True:
                call = submit_queue.get()
                # Wait for the actor to come alive (or die trying):
                # condition-signalled by start_actor, with a periodic
                # timeout to notice DEAD records.
                actor = self._actors.get(actor_id)
                deadline = time.monotonic() + 300.0
                while actor is None and time.monotonic() < deadline:
                    rec = self.gcs.get_actor(actor_id)
                    if rec is None or rec.state == "DEAD":
                        break
                    with self._actors_changed:
                        actor = self._actors.get(actor_id)
                        if actor is None:
                            self._actors_changed.wait(0.25)
                            actor = self._actors.get(actor_id)
                if actor is None:
                    err = ActorDiedError(actor_id, "actor failed to start")
                    for rid in call.return_ids:
                        self.store.put_error(rid, err)
                    call = None  # see below
                    continue
                # Resolve ObjectRef args in queue order (blocking keeps order).
                try:
                    if getattr(actor, "resolves_refs", False):
                        # Remote actors convert refs to FetchRef
                        # location hints themselves (node-to-node
                        # pulls); here just wait for the deps to seal
                        # WITHOUT materializing remote blobs locally.
                        for dep in [a for a in call.args
                                    if isinstance(a, ObjectRef)] + [
                                v for v in call.kwargs.values()
                                if isinstance(v, ObjectRef)]:
                            self.store.get(dep.id())
                    else:
                        call.args, call.kwargs, _ = resolve_args(
                            call.args, call.kwargs,
                            lambda ref: self.get([ref])[0])
                except BaseException as exc:  # noqa: BLE001
                    for rid in call.return_ids:
                        self.store.put_error(rid, exc)
                    call = None
                    continue
                actor.submit(call)
                # Unbind before blocking in get(): the stale frame
                # local would otherwise keep the LAST call's args —
                # and any ObjectRefs nested in them — registered until
                # the next call arrives, pinning freed objects.
                call = None

        # The drain thread is long-lived per actor, but its START is
        # offloaded: Thread.start blocks until the child's bootstrap
        # gets scheduled, and on a loaded box that stall lands on every
        # first method call of a creation wave. The queue buffers calls
        # until the drain attaches.
        drain_thread = threading.Thread(
            target=drain, daemon=True,
            name=f"ray_tpu-actor-submit-{actor_id.hex()[:8]}")
        self._thread_start_pool.submit(drain_thread.start)
        return submit_queue

    def execution_pipeline_stats(self) -> dict:
        """Driver-side per-stage drain counters for the pipelined
        execute path (the daemon-side stages live in each node's
        ``executor_stats()['pipeline']``): submit = the submit ring,
        dispatch = scheduler batch coalescing, seal = grouped result
        sealing."""
        return {
            "submit": self._submit_stats(),
            "dispatch": self._dispatch_stats(),
            "seal": {
                "batch_seals": self.store.batch_seals,
                "batch_sealed_objects": self.store.batch_sealed_objects,
            },
            # Fused in-daemon execution, accumulated from the batch
            # RPCs' done replies: batch RPCs whose runs fused at least
            # one task, tasks executed on daemon dispatch threads, and
            # fused-eligible entries that fell back to the worker
            # pipeline when a run's wall budget expired.
            "fused": self._fused_stats(),
            # Placement decisions (locality/load scoring) + straggler
            # speculation outcomes — the observability loop's own
            # observability (also exported as the
            # ray_tpu_sched_decisions_total /metrics family).
            "sched": self._sched_stats(),
        }

    def _submit_stats(self) -> dict:
        """Submit-stage counters (SUBMIT_STAT_KEYS): the classic ring,
        the columnar intake (ISSUE 15) and the cumulative flush wall
        — flush latency derives as flush_wall_us over flushes."""
        ring = self._submit_ring
        return {
            "ring_submits": ring.submits if ring else 0,
            "flushes": ring.flushes if ring else 0,
            "flush_tasks": ring.flush_tasks if ring else 0,
            "ring_full_waits": ring.ring_full_waits if ring else 0,
            "buffered_cancels": (ring.buffered_cancels if ring else 0)
            + self._col_buffered_cancels,
            "arg_cache_hits": self.arg_cache_hits,
            "col_submits": self._col_submits,
            "col_flush_tasks": self._col_flush_tasks,
            "flush_wall_us": self._flush_wall_us,
        }

    def _dispatch_stats(self) -> dict:
        """Dispatch-stage counters (DISPATCH_STAT_KEYS): classic batch
        coalescing plus the sharded lanes' occupancy/throughput.
        batch_tasks and batch_overcommit span BOTH engines (the
        >4-tasks/RPC invariant is engine-agnostic)."""
        lanes = self._lanes
        lane_stats = lanes.stats() if lanes is not None else {}
        return {
            "batches": self.dispatcher.batches_launched,
            "batch_tasks": self.dispatcher.batch_tasks_launched
            + lane_stats.get("lane_tasks", 0),
            "singles": self.dispatcher.singles_launched,
            "batch_overcommit": self.dispatcher.batch_overcommit
            + lane_stats.get("lane_overcommits", 0),
            # Deadline-heap sweeps that actually ran (the zero-armed
            # fast path skips them outright).
            "deadline_sweeps": self.dispatcher.deadline_sweeps,
            "lanes": lane_stats.get("lanes", 0),
            "lane_dispatches": lane_stats.get("lane_dispatches", 0),
            "lane_tasks": lane_stats.get("lane_tasks", 0),
            "lane_busy_us": lane_stats.get("lane_busy_us", 0),
            "lane_overcommits": lane_stats.get("lane_overcommits", 0),
            "col_groups": lane_stats.get("col_groups", 0),
            "lane_outstanding": lane_stats.get("lane_outstanding", 0),
        }

    def _fused_stats(self) -> dict:
        with self._fault_lock:
            return {
                "fused_runs": self._fused_runs,
                "fused_tasks": self._fused_tasks,
                "fused_fallbacks": self._fused_fallbacks,
            }

    def _sched_stats(self) -> dict:
        out = dict(self.cluster.sched_counters())
        watcher = self._spec_watcher
        if watcher is not None:
            out.update(watcher.counters())
        else:
            out.update({"speculations_launched": 0,
                        "speculations_won": 0,
                        "speculations_lost": 0})
        return out

    def fault_stats(self) -> dict:
        """Driver-side failure counters, same shape as the daemon's
        executor_stats()["faults"]: how often each recovery path fired
        in this process. The deterministic chaos tests assert these;
        the envelope records them per row."""
        from ray_tpu._private.rpc import breaker_stats, rpc_retry_count

        with self._fault_lock:
            batch_requeues = self._fault_batch_requeues
            task_timeouts = self._task_timeouts
            admission_shed = self._admission_shed
        return {
            "rpc_retries": rpc_retry_count(),
            "batch_requeues": batch_requeues,
            "peer_blacklists": 0,  # drivers pull whole blobs, not chunks
            "lease_orphans_swept": self._export_leases.expired,
            "lineage_rebuilds": self.recovery.num_recoveries,
            # Overload-control plane: deadline-sealed tasks (driver-side
            # seals, all stages), admission sheds (driver + daemon
            # replies), and circuit-breaker opens in this process.
            "task_timeouts": task_timeouts,
            "admission_shed": admission_shed,
            "breaker_open": breaker_stats()["opens"],
        }

    def _release_actor_lease(self, actor_id: ActorID) -> None:
        """Give back an actor's resource lease (idempotent)."""
        lease = self._actor_leases.pop(actor_id, None)
        if lease is None:
            return
        node_id, resources, pg_info = lease
        if pg_info is not None:
            self.placement_groups.release_to_bundle(
                pg_info[0], pg_info[1], resources)
        else:
            self.cluster.release(node_id, resources)

    def _record_actor_placement(self, record, actor, node_id) -> None:
        """Actor-table placement columns (reference: the GCS actor
        table records the executing address, gcs_actor_manager.h).
        The creation path and the async fillers (RemoteActor's create
        reply, ProcessActor's spawn) all funnel through here: the lock
        plus fresh reads of the actor's own attributes mean the last
        writer always records current values — a thread that captured
        state before a relocation can't overwrite the relocated
        placement with its stale copy."""
        # FIRST: async fillers race this method and must find the
        # record to complete it.
        actor._gcs_record = record
        with self._placement_record_lock:
            current = getattr(actor, "node_id", None) or node_id
            if current is None:
                # Local/process actors don't carry a node attribute;
                # their placement is wherever their lease sits (the
                # driver's node unless relocated).
                lease = self._actor_leases.get(record.actor_id)
                if lease is not None:
                    current = lease[0]
            if current is not None:
                record.node_id_hex = current.hex()
            pid = getattr(actor, "pid", None)
            if pid is None and getattr(actor, "_worker", None) is not None:
                pid = actor._worker.proc.pid
            if (pid is None and not hasattr(actor, "_worker")
                    and not hasattr(actor, "pid")):
                pid = os.getpid()  # thread actor: runs in this process
            if pid is not None:
                record.pid = pid
            record.num_restarts = getattr(actor, "_num_restarts", 0)

    def _relocate_actor_lease(self, actor_id: ActorID,
                              resources: dict[str, float],
                              exclude: set | None = None,
                              timeout: float = 300.0):
        """Move a remote actor's resource lease to a (different) worker
        daemon: release the current lease, acquire on another remote
        node. Returns (node_id, handle) or None when no remote node can
        host it within the timeout (reference: GcsActorScheduler re-
        schedules restarting actors onto surviving nodes)."""
        lease = self._actor_leases.pop(actor_id, None)
        if lease is not None:
            old_node, old_resources, old_pg = lease
            if old_pg is not None:
                # A placement-group actor is pinned to its bundle: it
                # may only be recreated where the bundle lives, never
                # silently relocated outside the gang (STRICT_* co-
                # location contracts). If the bundle's node is gone the
                # TERMINAL sentinel makes the actor die — group-level
                # recovery (FailureConfig) re-forms the whole gang,
                # slice semantics. (Plain None would send the caller's
                # retry loop through the generic path and silently
                # un-pin the actor.)
                self.placement_groups.release_to_bundle(
                    old_pg[0], old_pg[1], old_resources)
                try:
                    node_id = self.placement_groups.acquire_from_bundle(
                        old_pg[0], old_pg[1], resources)
                except Exception:  # noqa: BLE001 — bundle gone
                    return "pg_dead"
                node_state = self.cluster.get_node(node_id)
                with self._remote_nodes_lock:
                    handle = self._remote_nodes.get(node_id)
                if (handle is None or node_state is None
                        or not node_state.alive
                        or (exclude and node_id in exclude)):
                    self.placement_groups.release_to_bundle(
                        old_pg[0], old_pg[1], resources)
                    return "pg_dead"
                self._actor_leases[actor_id] = (node_id, resources, old_pg)
                return node_id, handle
            self.cluster.release(old_node, old_resources)
        deadline = time.monotonic() + timeout
        exclude = set(exclude or ())
        while True:
            with self._remote_nodes_lock:
                remote_ids = set(self._remote_nodes)
            # Only worker daemons can host a RemoteActor.
            local_ids = {n.node_id for n in self.cluster.nodes()
                         if n.node_id not in remote_ids}
            node = self.cluster.pick_node(
                resources, SchedulingStrategy(),
                exclude=local_ids | exclude)
            if node is not None and self.cluster.try_acquire(
                    node.node_id, resources):
                with self._remote_nodes_lock:
                    handle = self._remote_nodes.get(node.node_id)
                if handle is None:  # dropped between pick and acquire
                    self.cluster.release(node.node_id, resources)
                else:
                    self._actor_leases[actor_id] = (
                        node.node_id, resources, None)
                    return node.node_id, handle
            if time.monotonic() > deadline:
                return None
            self.cluster.wait_for_change(0.1)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        actor = self._actors.get(actor_id)
        if actor is not None:
            actor.kill("killed via kill()", no_restart=no_restart)
        else:
            self.gcs.remove_actor(actor_id)

    def get_actor_handle(self, name: str, namespace: str | None = None):
        from ray_tpu.actor import ActorHandle, ForeignActorHandle

        ns = namespace or self.namespace
        record = self.gcs.get_named_actor(name, ns)
        if record is not None:
            return ActorHandle(record.actor_id, record.class_name)
        # Cluster actor directory: the actor may live in ANOTHER
        # driver's runtime (reference: named actors resolve through the
        # GCS actor table, gcs_actor_manager.h).
        if self.gcs_client is not None:
            import pickle

            try:
                blob = self.gcs_client.call(
                    "kv_get", f"{ns}/{name}".encode(), "named_actors")
            except Exception:  # noqa: BLE001 — head unreachable
                blob = None
            if blob is not None:
                info = pickle.loads(blob)
                if info["owner_addr"] == self._client_server_addr():
                    # Our own published actor (registered under this
                    # driver): serve it locally.
                    return ActorHandle(
                        ActorID(bytes.fromhex(info["actor_key"])),
                        info["class_name"])
                return ForeignActorHandle(
                    info["owner_addr"], info["actor_key"],
                    info["class_name"],
                    method_meta=info.get("method_meta", {}))
        raise ValueError(f"Failed to look up actor with name {name!r}")

    def _client_server_addr(self) -> str:
        if self.worker_client_server is None:
            return ""
        from ray_tpu._private.node import _own_address

        return f"{_own_address()}:{self.worker_client_server.port}"

    def _publish_named_actor(self, record) -> None:
        """Advertise a named actor in the cluster directory (GCS KV)."""
        if self.gcs_client is None or self.worker_client_server is None:
            return
        import pickle

        entry = pickle.dumps({
            "actor_key": record.actor_id.hex(),
            "class_name": record.class_name,
            "owner_addr": self._client_server_addr(),
            # Per-method defaults (num_returns) so foreign callers match
            # local ActorHandle semantics.
            "method_meta": dict(record.method_meta),
        })
        try:
            self.gcs_client.call(
                "kv_put", f"{record.namespace}/{record.name}".encode(),
                entry, "named_actors")
        except Exception:  # noqa: BLE001 — best-effort advertisement
            logger.warning("failed to publish named actor %s",
                           record.name)

    def _unpublish_named_actor(self, namespace: str, name: str) -> None:
        if self.gcs_client is None:
            return
        try:
            self.gcs_client.call(
                "kv_del", f"{namespace}/{name}".encode(), "named_actors")
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    def submit_foreign_actor_task(self, owner_addr: str, actor_key: str,
                                  method_name: str, args: tuple,
                                  kwargs: dict,
                                  num_returns: int = 1) -> list[ObjectRef]:
        """Call an actor owned by another driver: ordered per-handle
        proxy thread drives the owner's client server and seals the
        results into OUR store as they arrive."""
        return_ids = [ObjectID() for _ in range(max(1, num_returns))]
        for rid in return_ids:
            self.store.create_pending(rid)
        refs = [ObjectRef(rid) for rid in return_ids]
        key = (owner_addr, actor_key)
        with self._futures_lock:
            proxy = self._foreign_proxies.get(key)
            if proxy is None:
                proxy = _ForeignActorProxy(self, owner_addr, actor_key)
                self._foreign_proxies[key] = proxy
        proxy.submit(method_name, args, kwargs, return_ids)
        return refs

    def kill_foreign_actor(self, owner_addr: str, actor_key: str) -> None:
        from ray_tpu._private.rpc import RpcClient

        client = RpcClient(owner_addr, timeout_s=30.0)
        try:
            client.call("client_kill_actor", actor_key)
        finally:
            client.close()

    # ------------------------------------------------------------ get/put/…

    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed")
        object_id = ObjectID()
        self.store.put(object_id, value)
        return ObjectRef(object_id)

    def get(self, refs: Sequence[ObjectRef], timeout: float | None = None) -> list[Any]:
        block_ctx = BlockedResourceContext.current()
        results = []
        deadline = None if timeout is None else time.monotonic() + timeout
        for ref in refs:
            if not isinstance(ref, ObjectRef):
                raise TypeError(
                    f"get() expects ObjectRef (or list of them), got {type(ref)}")
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if self.store.contains(ref.id()):
                results.append(self._materialize_value(
                    ref.id(), self.store.get(ref.id())))
                continue
            if block_ctx is not None:
                block_ctx.block()
            try:
                results.append(self._materialize_value(
                    ref.id(), self.store.get(ref.id(), timeout=remaining)))
            finally:
                if block_ctx is not None:
                    block_ctx.unblock()
        return results

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: float | None = None) -> tuple[list[ObjectRef], list[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError(
                f"num_returns={num_returns} exceeds the number of refs ({len(refs)})")
        by_id = {ref.id(): ref for ref in refs}
        block_ctx = BlockedResourceContext.current()
        if block_ctx is not None:
            block_ctx.block()
        try:
            ready_ids, not_ready_ids = self.store.wait(
                [r.id() for r in refs], num_returns, timeout)
        finally:
            if block_ctx is not None:
                block_ctx.unblock()
        return ([by_id[i] for i in ready_ids], [by_id[i] for i in not_ready_ids])

    def cancel(self, ref: ObjectRef) -> None:
        # Best-effort: only not-yet-dispatched tasks can be cancelled in the
        # thread-worker slice (threads are not preemptible). A task that is
        # already running completes normally — matching non-force cancel in
        # the reference.
        ring = self._submit_ring
        if ring is not None and ring.cancel(ref.id()) is not None:
            # Still buffered (or mid-flush): the ring owns the cancel —
            # buffered records seal TaskCancelledError immediately,
            # draining ones via the flush's post-pass.
            return
        if self._lanes is not None and self._cancel_columnar(ref.id()):
            return
        self._cancel_registered(ref.id())

    def free(self, refs: Sequence[ObjectRef]) -> None:
        self.store.free([r.id() for r in refs])
        self.lineage.forget([r.id() for r in refs])
        with self._locations_lock:
            for r in refs:
                self._object_locations.pop(r.id(), None)
        for r in refs:
            desc = self.shm_directory.lookup(r.id())
            if desc is not None:
                self.shm_client.close_segment(desc.name)
                self.shm_directory.free(r.id())
            if self._export_store is not None:
                self._export_store.free([r.id().binary()])
            self._drop_export_source(r.id().binary())

    # -------------------------------------------------------------- futures

    def attach_future(self, ref: ObjectRef, fut: concurrent.futures.Future) -> None:
        ring = self._submit_ring
        with self._futures_lock:
            if not self.store.contains(ref.id()) and (
                    self.store.is_pending(ref.id())
                    or (ring is not None and ring.holds(ref.id()))
                    or ref.id() in self._col_index):
                # A ring-buffered submit has no store entry yet but IS
                # pending — its flush creates the entry and the seal
                # listener resolves the future.
                self._futures.setdefault(ref.id(), []).append(fut)
                return
        # Already sealed (or unknown): resolve immediately.
        self._resolve_one_future(ref.id(), fut)

    def _resolve_futures(self, object_id: ObjectID) -> None:
        with self._futures_lock:
            futs = self._futures.pop(object_id, [])
        for fut in futs:
            self._resolve_one_future(object_id, fut)

    def _resolve_one_future(self, object_id: ObjectID, fut) -> None:
        try:
            value = self._materialize_value(
                object_id, self.store.get(object_id, timeout=0))
            fut.set_result(value)
        except BaseException as exc:  # noqa: BLE001
            try:
                fut.set_exception(exc)
            except Exception:
                pass  # future already resolved by a racing seal

    # --------------------------------------------------------------- status

    def cluster_resources(self) -> dict[str, float]:
        return self.cluster.total_resources()

    def available_resources(self) -> dict[str, float]:
        return self.cluster.available_resources()

    def shutdown(self) -> None:
        if self._spec_watcher is not None:
            self._spec_watcher.stop()
        if self._submit_ring is not None:
            # Flush buffered submits (their owners may still hold refs)
            # and retire the submitter before the planes below close.
            ring, self._submit_ring = self._submit_ring, None
            ring.stop()
        if self._lanes is not None:
            self._lanes.shutdown()
        self._watcher_stop.set()
        with self._remote_nodes_lock:
            handles = list(self._remote_nodes.values())
            self._remote_nodes.clear()
        for handle in handles:
            handle.close()
        if self._obj_server is not None:
            self._obj_server.stop()
            self._obj_server = None
        for proxy in list(self._foreign_proxies.values()):
            proxy.close()
        self._foreign_proxies.clear()
        # Kill actors while the GCS connection is still open: their
        # on_death hooks unpublish cluster named-actor entries, which
        # would otherwise go stale forever.
        for actor in list(self._actors.values()):
            actor.kill("runtime shutdown", no_restart=True)
        if self._node_agent is not None:
            self._node_agent.stop()
            self._node_agent = None
        if self.gcs_client is not None:
            self.gcs_client.close()
            self.gcs_client = None
        if self.dashboard is not None:
            self.dashboard.stop()
            self.dashboard = None
        if self.metrics_agent is not None:
            self.metrics_agent.shutdown()
        self.health_monitor.shutdown()
        self.dispatcher.shutdown()
        # Spill tier: retire the spiller threads and drop this
        # session's spill files (the per-pid dir would otherwise wait
        # for a survivor's orphan sweep after the process exits).
        for mgr in (getattr(self.store, "_spill", None),
                    self._export_spill_mgr):
            if mgr is not None:
                mgr.stop()
        from ray_tpu._private import spill_manager as _spill_mod

        if _spill_mod.live_manager_count() == 0:
            # Last manager in this process: the per-pid dir holds no
            # live store's files anymore (in-process executors would
            # still be registered).
            import shutil as _shutil

            _shutil.rmtree(_spill_mod.process_spill_dir(),
                           ignore_errors=True)
        if self.memory_monitor is not None:
            self.memory_monitor.stop()
        if self.worker_pool is not None:
            self.worker_pool.shutdown()
        if self.worker_client_server is not None:
            self.worker_client_server.stop()
            os.environ.pop("RAY_TPU_DRIVER_CLIENT_ADDR", None)
            self.worker_client_server = None
        if self.log_monitor is not None:
            self.log_monitor.stop()
            os.environ.pop("RAY_TPU_WORKER_LOG_DIR", None)
            import shutil

            shutil.rmtree(os.path.dirname(self.log_monitor.log_dir),
                          ignore_errors=True)
            self.log_monitor = None
        self.shm_client.close_all()
        self.shm_directory.shutdown()
        # Export twins: leases die with the runtime; segments must be
        # unlinked here or they outlive the process in /dev/shm. The
        # export store's memoryviews into them are dropped FIRST so the
        # close doesn't trip on exported pointers.
        self._export_leases.clear()
        with self._export_lock:
            export_ids = list(self._export_segments)
            export_segs = list(self._export_segments.values())
            self._export_segments.clear()
            self._export_sources.clear()
        if self._export_store is not None and export_ids:
            self._export_store.free(export_ids)
        for seg in export_segs:
            try:
                seg.unlink()
            except (OSError, FileNotFoundError):
                pass  # segment already unlinked by the tracker
            try:
                seg.close()
            except (BufferError, OSError):
                from ray_tpu._private.shm_store import _defuse

                _defuse(seg)
        if self.arena is not None:
            self.arena.close()  # owner: destroys the shared arena
            os.environ.pop("RAY_TPU_ARENA_NAME", None)
            self.arena = None
        self.gcs.finish_job(self.job_id)


class _RemoteBlockContext(BlockedResourceContext):
    """Block context for a task executing on a worker-node daemon: a
    nested blocked get() releases the task's CPU on the driver's
    cluster ledger (base class) AND on the daemon's admission ledger
    (task_block/task_unblock RPCs), so dependent work can be admitted
    to the same daemon while the parent waits."""

    def __init__(self, cluster, node_id, resources, handle, token):
        super().__init__(cluster, node_id, resources)
        self._handle = handle
        self._token = token

    def _on_release(self):
        try:
            self._handle._control.call("task_block", self._token)
        except Exception:  # noqa: BLE001 — daemon gone; best-effort
            pass

    def _on_reacquire(self):
        try:
            self._handle._control.call("task_unblock", self._token)
        except Exception:  # noqa: BLE001 — daemon gone; best-effort
            pass


class _ForeignActorProxy:
    """Ordered call pipe to one foreign actor: a drain thread issues
    client_actor_call + long-poll gets against the owning driver's
    client server and seals results into the local store (the foreign
    analogue of the per-actor submit queue,
    transport/sequential_actor_submit_queue.h)."""

    def __init__(self, runtime: "Runtime", owner_addr: str,
                 actor_key: str):
        import queue as queue_mod

        from ray_tpu._private.rpc import RpcClient

        self._runtime = runtime
        self._actor_key = actor_key
        self._owner_addr = owner_addr
        self._rpc = RpcClient(owner_addr, timeout_s=60.0)
        self._queue: queue_mod.Queue = queue_mod.Queue()
        self._thread = threading.Thread(
            target=self._drain, daemon=True,
            name=f"ray_tpu-foreign-actor-{actor_key[:8]}")
        self._thread.start()

    def submit(self, method_name: str, args: tuple, kwargs: dict,
               return_ids: list[ObjectID]) -> None:
        self._queue.put((method_name, args, kwargs, return_ids))

    def close(self) -> None:
        self._queue.put(None)
        self._rpc.close()

    def _fail(self, return_ids, exc) -> None:
        for rid in return_ids:
            self._runtime.store.put_error(rid, exc)

    def _drain(self) -> None:
        from ray_tpu._private import serialization
        from ray_tpu._private.rpc import RpcError, RpcMethodError

        while True:
            item = self._queue.get()
            if item is None:
                return
            method_name, args, kwargs, return_ids = item
            sealed: set = set()
            try:
                # Resolve refs to values locally: the owner cannot
                # dereference OUR object ids.
                args, kwargs, _ = resolve_args(
                    args, kwargs, lambda r: self._runtime.get([r])[0])
                blob = serialization.serialize_framed((args, kwargs))
                keys = self._rpc.call(
                    "client_actor_call", self._actor_key, method_name,
                    blob, len(return_ids))
                if len(keys) != len(return_ids):
                    raise ValueError(
                        f"{method_name} returned {len(keys)} values but "
                        f"the handle expected {len(return_ids)} (declare "
                        f"num_returns via .options or @method)")
                for key, rid in zip(keys, return_ids):
                    while True:
                        status, vblob = self._rpc.call(
                            "client_get", [key], 10.0)
                        if status == "ok":
                            value = serialization.deserialize_from_buffer(
                                memoryview(vblob))[0]
                            self._runtime.store.put(rid, value)
                            sealed.add(rid)
                            break
                try:
                    self._rpc.call("client_release", keys)
                except (RpcError, RpcMethodError):
                    pass
            except RpcMethodError as exc:
                self._fail([r for r in return_ids if r not in sealed],
                           exc.cause)
            except (RpcError, OSError) as exc:
                # Never clobber results already delivered: only the
                # still-pending returns become errors.
                self._fail([r for r in return_ids if r not in sealed],
                           ActorDiedError(
                               None, f"owner driver at {self._owner_addr} "
                               f"unreachable: {exc}"))
            except BaseException as exc:  # noqa: BLE001
                self._fail([r for r in return_ids if r not in sealed],
                           exc)
            # Unbind before re-blocking in get(): stale frame locals
            # would keep the last call's args (and nested ObjectRefs)
            # alive until the next call arrives.
            item = args = kwargs = None


# --------------------------------------------------------------------------
# Module-level singleton API
# --------------------------------------------------------------------------


def global_runtime():
    if _runtime is not None:
        return _runtime
    if os.environ.get("RAY_TPU_IN_POOL_WORKER"):
        from ray_tpu._private import worker_client

        active = worker_client.active_worker_runtime()
        if active is not None:
            return active
        # Refs can deserialize BEFORE the worker's first explicit API
        # call (e.g. inside actor-constructor args); borrower
        # registration needs the proxy runtime to exist at that moment,
        # so build it eagerly when the driver address is known.
        if os.environ.get("RAY_TPU_DRIVER_CLIENT_ADDR"):
            try:
                return worker_client.get_worker_runtime()
            except Exception:  # noqa: BLE001 — keep refs inert instead
                return None
    return None


def init(
    *,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    object_store_memory: int | None = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    system_config: dict | None = None,
    logging_level: str | None = None,
    process_workers: int | None = None,
    metrics_port: int | None = None,
    dashboard_port: int | None = None,
    address: str | None = None,
    **_ignored,
) -> Runtime:
    """Initialize the runtime (reference: ray.init, worker.py:1219).

    ``address="host:port"`` connects to a running head's GCS
    (``python -m ray_tpu start --head``); ``address="auto"`` resolves it
    from RAY_TPU_ADDRESS or the local head's session file.
    """
    import os as _os

    if _os.environ.get("RAY_TPU_IN_POOL_WORKER"):
        # Inside a pool worker the public API proxies back to the driver
        # (reference: workers are full CoreWorkers and may submit tasks);
        # init() is a no-op returning the proxy runtime.
        if _os.environ.get("RAY_TPU_DRIVER_CLIENT_ADDR"):
            from ray_tpu._private import worker_client

            return worker_client.get_worker_runtime()
        raise RuntimeError(
            "ray_tpu.init() inside a pool worker requires the driver's "
            "client server (driver predates nested submission)")
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            if ignore_reinit_error:
                return _runtime
            raise RuntimeError(
                "ray_tpu.init() has already been called; pass "
                "ignore_reinit_error=True to ignore")
        if system_config:
            GLOBAL_CONFIG.update(system_config)
        if logging_level:
            logging.getLogger("ray_tpu").setLevel(logging_level)
        if bool(GLOBAL_CONFIG.tracing_enabled):
            # Arm the tracing plane up front (RAY_TPU_TRACING_ENABLED
            # or init(system_config={"tracing_enabled": True})); daemons
            # inherit the env through daemon_child_env.
            tracing.enable()
        if address == "auto":
            from ray_tpu.scripts import resolve_address

            try:
                address = resolve_address(None)
            except SystemExit as exc:
                # resolve_address is CLI-oriented; surface a catchable
                # library error here instead of exiting the process.
                raise ConnectionError(str(exc)) from None
        _runtime = Runtime(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
            object_store_memory=object_store_memory, namespace=namespace,
            process_workers=process_workers, metrics_port=metrics_port,
            dashboard_port=dashboard_port, address=address)
        atexit.register(_atexit_shutdown)
        return _runtime


def _atexit_shutdown():
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            try:
                _runtime.shutdown()
            except Exception:
                pass  # shutdown() is best-effort on interpreter exit
            _runtime = None


def shutdown() -> None:
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None


def is_initialized() -> bool:
    return _runtime is not None


def _require_runtime():
    if _runtime is None:
        if os.environ.get("RAY_TPU_IN_POOL_WORKER"):
            return init()  # worker-mode proxy runtime
        init()
    return _runtime  # type: ignore[return-value]


def auto_init() -> Runtime:
    return _require_runtime()


def put(value: Any) -> ObjectRef:
    return _require_runtime().put(value)


def get(refs, timeout: float | None = None):
    runtime = _require_runtime()
    if isinstance(refs, ObjectRef):
        return runtime.get([refs], timeout=timeout)[0]
    if isinstance(refs, (list, tuple)):
        return runtime.get(list(refs), timeout=timeout)
    raise TypeError(f"get() expects an ObjectRef or list of ObjectRefs, got {type(refs)}")


def wait(refs, *, num_returns: int = 1, timeout: float | None = None,
         fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return _require_runtime().wait(list(refs), num_returns=num_returns, timeout=timeout)


def kill(actor_handle, *, no_restart: bool = True) -> None:
    from ray_tpu.actor import ActorHandle, ForeignActorHandle

    if isinstance(actor_handle, ForeignActorHandle):
        _require_runtime().kill_foreign_actor(
            actor_handle._owner_addr, actor_handle._actor_key)
        return
    if not isinstance(actor_handle, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    _require_runtime().kill_actor(actor_handle._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    _require_runtime().cancel(ref)


def get_actor(name: str, namespace: str | None = None):
    return _require_runtime().get_actor_handle(name, namespace)


def cluster_resources() -> dict[str, float]:
    return _require_runtime().cluster_resources()


def available_resources() -> dict[str, float]:
    return _require_runtime().available_resources()


def nodes() -> list[dict]:
    runtime = _require_runtime()
    out = [
        {
            "NodeID": r.node_id.hex(),
            "Alive": r.alive,
            "Resources": dict(r.resources),
            "Labels": dict(r.labels),
            "NodeManagerAddress": r.address,
        }
        for r in runtime.gcs.list_nodes()
    ]
    if runtime.gcs_client is not None:
        from ray_tpu._private.rpc import RpcError

        try:
            for n in runtime.gcs_client.call("list_nodes"):
                out.append({
                    "NodeID": n["node_id"],
                    "Alive": n["alive"],
                    "Resources": n["resources"],
                    "Labels": n["labels"],
                    "NodeManagerAddress": n["address"],
                })
        except RpcError:
            pass  # head unreachable; local view only
    return out


def timeline() -> list[dict]:
    """Chrome-trace-style task events (reference: `ray timeline`).

    With tracing enabled, each task expands into per-stage slices
    (submit→dispatch→rpc→admit→worker→execute→seal) across one process
    lane per node, linked by flow arrows; untraced tasks keep the
    single-slice view. ``util.tracing.export_chrome_trace(path)``
    writes the same merged view (plus spans) to a file."""
    from ray_tpu.util import tracing as _tracing

    return _tracing.build_task_events(_require_runtime())
