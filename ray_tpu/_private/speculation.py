"""Straggler speculation — the driver-side watcher that closes the
observability loop on in-flight tasks.

TPU-native analogue of speculative execution in the lineage of the
Ray paper's stragglers discussion (arxiv 1712.05889) and Spark's
``spark.speculation``: an in-flight task whose elapsed wall exceeds
``speculation_p99_factor`` x the cluster-merged per-function p99 from
the perf plane (``perf_plane.record_task_wall`` — every node's
executions of the function land in the owner's sample ring) gets a
speculative copy re-dispatched to a DIFFERENT node. First seal wins
through the existing seal path:

- the sealing member calls :meth:`SpeculationWatcher.claim_win` BEFORE
  touching the store; the first claimant seals normally, the loser's
  seal is skipped (a nondeterministic function must not have its
  winning value overwritten by a late loser);
- the loser is cancelled best-effort: a still-queued copy via the
  dispatcher's O(1) cancel bookkeeping (no error is sealed — the
  winner's value already lives in the store), an in-flight one via the
  daemon's ``cancel_task`` token (checked before the user function
  runs, so a straggler held in admission/chaos delay provably never
  executes — the side-effect exactly-once property the chaos tests
  assert with marker files);
- a member that FAILS while its sibling is still live (e.g. the
  original's node died under it) is absorbed (:meth:`absorb_failure`)
  instead of sealing an error over a result the sibling can still
  produce — speculation doubles as a latency hedge against node death.

Disarmed cost is one module-attribute branch per site (``SPEC_ON`` —
the chaos.ACTIVE / perf_plane.PERF_ON discipline); the watcher thread
only exists while armed (``speculation_enabled``).

Counters (``execution_pipeline_stats()["sched"]``):
``speculations_launched`` / ``speculations_won`` (the copy sealed
first) / ``speculations_lost`` (the original beat its copy). Decisions
also land as instant pins in merged trace timelines while tracing is
armed.
"""

from __future__ import annotations

import logging
import threading
import time

from ray_tpu._private import perf_plane
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import TaskID
from ray_tpu._private.task import SchedulingStrategy, TaskSpec
from ray_tpu.util import tracing

logger = logging.getLogger("ray_tpu")

# The ONE production branch: every integration site in worker.py reads
# this module attribute and pays nothing else while disarmed.
SPEC_ON: bool = False


def enable() -> None:
    global SPEC_ON
    SPEC_ON = True


def disable() -> None:
    global SPEC_ON
    SPEC_ON = False


def init_from_config() -> None:
    global SPEC_ON
    SPEC_ON = bool(GLOBAL_CONFIG.speculation_enabled)


try:
    init_from_config()
except Exception:  # noqa: BLE001 — config unavailable mid-bootstrap
    pass


def should_speculate(elapsed_s: float, sample_count: int, p99_s: float,
                     factor: float, min_samples: int) -> bool:
    """The trigger math, factored out for direct test coverage: an
    in-flight elapsed wall past ``factor x p99`` triggers, but only
    once the function has a trustworthy sample base and a non-trivial
    p99 (a sub-millisecond p99 floor keeps noise from speculating
    every microtask)."""
    if sample_count < max(1, min_samples):
        return False
    return elapsed_s > factor * max(p99_s, 1e-3)


class _Tracked:
    __slots__ = ("spec", "node_id", "start", "copies", "no_speculate")

    def __init__(self, spec, node_id, no_speculate: bool):
        self.spec = spec
        self.node_id = node_id
        self.start = time.monotonic()
        self.copies = 0
        self.no_speculate = no_speculate


class _Pair:
    """One original/copy speculation pair, keyed by the shared return
    ids. ``winner`` is the member that claimed the seal first; ``done``
    holds the members whose lifecycle has fully resolved."""

    __slots__ = ("orig", "copy", "winner", "done", "failed")

    def __init__(self, orig, copy):
        self.orig = orig
        self.copy = copy
        self.winner = None
        self.done: set[int] = set()
        self.failed: set[int] = set()

    def other(self, spec):
        return self.copy if spec is self.orig else self.orig


class SpeculationWatcher:
    """Tracks in-flight tasks, launches speculative copies, resolves
    first-seal-wins. Owned by the Runtime; one daemon thread."""

    def __init__(self, runtime):
        self._runtime = runtime
        self._lock = threading.Lock()
        self._inflight: dict[int, _Tracked] = {}   # id(spec) -> entry
        self._pairs: dict = {}                     # return ObjectID -> _Pair
        self.launched = 0
        self.won = 0
        self.lost = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ray_tpu-speculation")
        self._thread.start()

    def counters(self) -> dict:
        with self._lock:
            return {"speculations_launched": self.launched,
                    "speculations_won": self.won,
                    "speculations_lost": self.lost}

    def stop(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------ tracking

    @staticmethod
    def _eligible(spec) -> bool:
        strategy = spec.scheduling_strategy
        if strategy is not None:
            if strategy.kind == "PLACEMENT_GROUP":
                return False  # bundle-pinned: a copy can't leave the gang
            if strategy.kind == "NODE_AFFINITY" \
                    and not getattr(strategy, "soft", False):
                return False  # hard pin can never run elsewhere
        return spec.func is not None and not spec.is_actor_task

    def track(self, spec, node) -> bool:
        """Register an in-flight execution (copies register too — their
        node is needed for loser cancellation — but never re-speculate).
        Returns True when the caller must untrack on completion."""
        if not self._eligible(spec):
            return False
        entry = _Tracked(
            spec, node.node_id if node is not None else None,
            no_speculate=getattr(spec, "_speculative_of", None)
            is not None)
        with self._lock:
            self._inflight[id(spec)] = entry
        return True

    def untrack(self, spec, completed: bool = False) -> None:
        with self._lock:
            entry = self._inflight.pop(id(spec), None)
        if entry is not None and completed:
            # Completed-wall sample for the perf plane's per-function
            # ring: the owner clock sees every node's executions, so
            # this IS the cluster-merged distribution the trigger
            # compares against. Only SUCCESSFUL completions feed it —
            # spillbacks and failures would skew the baseline short.
            perf_plane.record_task_wall(
                spec.name, time.monotonic() - entry.start)

    # -------------------------------------------------------- watcher loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                period = max(0.02, float(
                    GLOBAL_CONFIG.speculation_watch_period_ms) / 1000.0)
            except Exception:  # noqa: BLE001 — config mid-teardown
                period = 0.2
            if self._stop.wait(period):
                return
            if not SPEC_ON:
                continue
            try:
                self._sweep()
            except Exception:  # noqa: BLE001 — watcher must survive
                logger.exception("speculation sweep failed")

    def _sweep(self) -> None:
        factor = float(GLOBAL_CONFIG.speculation_p99_factor)
        max_copies = int(GLOBAL_CONFIG.speculation_max_copies)
        min_samples = int(GLOBAL_CONFIG.speculation_min_samples)
        now = time.monotonic()
        with self._lock:
            entries = list(self._inflight.values())
        for entry in entries:
            if entry.no_speculate or entry.copies >= max_copies:
                continue
            spec = entry.spec
            if not spec.return_ids:
                continue
            with self._lock:
                if spec.return_ids[0] in self._pairs:
                    continue  # already speculated (bounded per task)
            count, p99 = perf_plane.wall_quantile(spec.name, 0.99)
            if not should_speculate(now - entry.start, count, p99,
                                    factor, min_samples):
                continue
            self._launch_copy(entry, p99)

    def _launch_copy(self, entry: _Tracked, p99_s: float) -> None:
        runtime = self._runtime
        spec = entry.spec
        avoid = {entry.node_id} if entry.node_id is not None else set()
        # A copy is only worth launching when a DIFFERENT node could
        # actually host it.
        if not any(n.node_id not in avoid and n.feasible(spec.resources)
                   for n in runtime.cluster.nodes()):
            return
        copy = TaskSpec(
            task_id=TaskID(), name=spec.name, func=spec.func,
            args=spec.args, kwargs=spec.kwargs,
            num_returns=spec.num_returns,
            resources=dict(spec.resources),
            scheduling_strategy=SchedulingStrategy(),
            return_ids=list(spec.return_ids),
            runtime_env=spec.runtime_env, deadline=spec.deadline)
        copy._speculative_of = spec.task_id
        copy._avoid_nodes = set(avoid)
        pair = _Pair(spec, copy)
        with self._lock:
            entry.copies += 1
            for rid in spec.return_ids:
                self._pairs[rid] = pair
            self.launched += 1
        from ray_tpu._private.gcs import TaskEvent

        runtime.gcs.record_task_event(TaskEvent(
            copy.task_id, spec.name, "PENDING"))
        if tracing.TRACE_ON:
            tracing.instant("sched:speculate", {
                "task": spec.task_id.hex()[:16], "name": spec.name,
                "p99_s": round(p99_s, 6)})
        from ray_tpu._private.object_ref import ObjectRef

        deps = [a for a in spec.args if isinstance(a, ObjectRef)] + [
            v for v in spec.kwargs.values() if isinstance(v, ObjectRef)]
        runtime.dispatcher.submit(copy, runtime._execute_task, deps)
        logger.info(
            "speculating task %s (%s): elapsed > %gx p99 (%.3fs), copy "
            "avoids node %s", spec.name, spec.task_id.hex()[:8],
            float(GLOBAL_CONFIG.speculation_p99_factor), p99_s,
            entry.node_id.hex()[:8] if entry.node_id else "?")

    # ----------------------------------------------------- first-seal-wins

    def _pair_of(self, spec):
        # Caller holds self._lock.
        if not spec.return_ids:
            return None
        return self._pairs.get(spec.return_ids[0])

    def _cleanup_locked(self, pair: _Pair) -> None:
        if len(pair.done) >= 2:
            for rid in pair.orig.return_ids:
                self._pairs.pop(rid, None)

    def claim_win(self, spec) -> bool:
        """Called by every seal path BEFORE writing results. True =>
        seal normally (no pair, or this member claimed the win first);
        False => a sibling already sealed — skip the write entirely."""
        cancel_loser = None
        with self._lock:
            pair = self._pair_of(spec)
            if pair is None:
                return True
            member = spec if spec in (pair.orig, pair.copy) else None
            if member is None:
                return True
            if pair.winner is None:
                pair.winner = member
                pair.done.add(id(member))
                if member is pair.copy:
                    self.won += 1
                else:
                    self.lost += 1
                cancel_loser = pair.other(member)
                loser_entry = self._inflight.get(id(cancel_loser))
                loser_node = loser_entry.node_id if loser_entry else None
            elif pair.winner is member:
                return True  # idempotent reseal by the winner
            else:
                pair.done.add(id(member))
                self._cleanup_locked(pair)
                return False
        if tracing.TRACE_ON:
            tracing.instant(
                "sched:speculation_" + (
                    "won" if spec is not pair.orig else "lost"),
                {"task": pair.orig.task_id.hex()[:16],
                 "name": pair.orig.name})
        if cancel_loser is not None:
            self._cancel_loser(pair, cancel_loser, loser_node)
        return True

    def _cancel_loser(self, pair: _Pair, loser, loser_node) -> None:
        """Best-effort loser cancellation. A still-queued loser is
        flagged via the dispatcher's O(1) cancel bookkeeping (NO error
        seal — the winner's value is already in the store); an
        in-flight one gets its task token cancelled at its daemon so
        an execution that hasn't started yet never does."""
        runtime = self._runtime
        cancelled = runtime.dispatcher.cancel_by_return_id(
            loser.return_ids[0])
        if cancelled is not None:
            with self._lock:
                pair.done.add(id(loser))
                self._cleanup_locked(pair)
            return
        if loser_node is None:
            return
        with runtime._remote_nodes_lock:
            handle = runtime._remote_nodes.get(loser_node)
        if handle is None:
            return
        token = loser.task_id.hex()

        def rpc_cancel():
            try:
                handle._control.call("cancel_task", token)
            except Exception:  # noqa: BLE001 — best-effort
                pass

        threading.Thread(target=rpc_cancel, daemon=True,
                         name="ray_tpu-spec-cancel").start()

    def mark_cancelled(self, spec) -> None:
        """The daemon refused ``spec``'s execution because its token
        was cancelled (it lost the race before ever running)."""
        with self._lock:
            pair = self._pair_of(spec)
            if pair is not None and spec in (pair.orig, pair.copy):
                pair.done.add(id(spec))
                self._cleanup_locked(pair)

    def absorb_failure(self, spec) -> bool:
        """Called by the failure path BEFORE retry/seal. True => the
        failure is absorbed (a sibling already won, or is still live
        and may yet produce the result); False => this was the last
        live member — fail normally."""
        with self._lock:
            pair = self._pair_of(spec)
            if pair is None or spec not in (pair.orig, pair.copy):
                return False
            other = pair.other(spec)
            if pair.winner is not None and pair.winner is not spec:
                pair.done.add(id(spec))
                self._cleanup_locked(pair)
                return True
            if id(other) not in pair.failed and pair.winner is None:
                # Sibling still live (queued or running): hedge holds.
                pair.failed.add(id(spec))
                pair.done.add(id(spec))
                return True
            # Last member standing failed too: surface the error.
            pair.done.add(id(spec))
            self._cleanup_locked(pair)
            return False
