"""ctypes wrapper over the native (C++) node object store.

Reference: the raylet's local object store is C++
(src/ray/object_manager/object_store.h) — this replaces the daemon's
Python blob store with ray_tpu/_native/node_store.cpp, keeping the
EXACT NodeObjectStore interface (put/get/free/free_owner/owners/
read_chunk/stats) so NodeExecutorService treats both uniformly.
Because ctypes releases the GIL around calls, store reads never block
the daemon's Python threads, and spilled-file restores stream outside
the store mutex — the wins show on multi-core daemon hosts.
Single-threaded, ctypes marshalling makes raw reads a few GB/s vs the
Python store's in-GIL slice (~12 GB/s); both are orders of magnitude
above the socket+pickle transfer path they feed, so end-to-end
throughput is identical (measured: the distributed test suites run in
the same time on either store).
"""

from __future__ import annotations

import ctypes
import os


class NativeNodeObjectStore:
    """Drop-in native implementation of NodeObjectStore."""

    def __init__(self, lib, cache_limit_bytes: int | None = None,
                 primary_limit_bytes: int | None = None,
                 spill_dir: str | None = None):
        from ray_tpu._private.config import GLOBAL_CONFIG

        self._lib = lib
        cache = (cache_limit_bytes if cache_limit_bytes is not None
                 else int(GLOBAL_CONFIG.node_pull_cache_mb) * 1024 * 1024)
        primary = (primary_limit_bytes if primary_limit_bytes is not None
                   else int(GLOBAL_CONFIG.node_store_primary_limit_mb)
                   * 1024 * 1024)
        self._spill_dir = spill_dir or GLOBAL_CONFIG.node_store_spill_dir
        os.makedirs(self._spill_dir, exist_ok=True)
        purge_stale_spills(self._spill_dir)
        self._handle = lib.rt_ns_create(cache, primary,
                                        self._spill_dir.encode())
        if not self._handle:
            raise RuntimeError("native node store creation failed")
        self._closed = False

    @staticmethod
    def _key(id_bytes: bytes) -> bytes:
        if len(id_bytes) == 16:
            return id_bytes
        # Foreign-length keys (tests, export hashes) fold to 16 bytes.
        import hashlib

        return hashlib.blake2b(id_bytes, digest_size=16).digest()

    def put(self, id_bytes: bytes, blob: bytes, cached: bool = False,
            owner: str | None = None) -> None:
        if self._closed:
            return
        self._lib.rt_ns_put(self._handle, self._key(id_bytes), blob,
                            len(blob), 1 if cached else 0,
                            (owner or "").encode())

    def _read_into(self, key: bytes, offset: int, length: int):
        """-> (total, bytearray) with ONE copy (C++ writes straight
        into the Python-owned buffer), or None when absent."""
        ba = bytearray(max(1, length))
        cbuf = (ctypes.c_char * len(ba)).from_buffer(ba)
        copied = ctypes.c_uint64()
        total = self._lib.rt_ns_read(
            self._handle, key, offset,
            ctypes.cast(cbuf, ctypes.POINTER(ctypes.c_uint8)), length,
            ctypes.byref(copied))
        if total < 0:
            return None
        if copied.value != len(ba):
            # Short read (tail chunk): SLICE — the ctypes buffer export
            # may still pin ba, so resizing it raises BufferError.
            return int(total), ba[:copied.value]
        return int(total), ba

    def get(self, id_bytes: bytes) -> bytes | None:
        if self._closed:
            return None
        key = self._key(id_bytes)
        size = self._lib.rt_ns_size(self._handle, key)
        for _ in range(8):
            if size < 0:
                return None
            out = self._read_into(key, 0, size)
            if out is None:
                return None  # freed between size and read
            total, ba = out
            if total == size and len(ba) == size:
                return bytes(ba)
            if total == size:
                # Short copy at unchanged size: a spilled file came up
                # truncated (I/O error) — surface absence, never a
                # silently corrupt blob.
                return None
            # A concurrent reseal changed the object's size between the
            # size probe and the copy; retry at the new size (the
            # Python store does size+copy atomically under one lock).
            size = total
        return None

    def free(self, ids: list[bytes]) -> int:
        if not ids or self._closed:
            return 0
        packed = b"".join(self._key(i) for i in ids)
        return self._lib.rt_ns_free(self._handle, packed, len(ids))

    def free_owner(self, owner: str) -> int:
        if self._closed:
            return 0
        return self._lib.rt_ns_free_owner(self._handle, owner.encode())

    def owners(self) -> list[str]:
        if self._closed:
            return []
        # The set may change between sizing and filling; retry with the
        # SECOND call's own length until it fits (a stale first length
        # would otherwise leave truncated/garbage owner names).
        buflen = 256
        for _ in range(8):
            buf = ctypes.create_string_buffer(buflen)
            got = self._lib.rt_ns_owners(self._handle, buf, buflen)
            if got <= 0:
                return []
            if got <= buflen:
                return buf.raw[:got].decode().split("\n")
            buflen = int(got) * 2
        return []

    def size(self, id_bytes: bytes) -> int | None:
        """Blob size without copying (transfer-plan probes)."""
        if self._closed:
            return None
        total = self._lib.rt_ns_size(self._handle, self._key(id_bytes))
        return None if total < 0 else int(total)

    def read_chunk(self, id_bytes: bytes, offset: int,
                   length: int) -> tuple[int, "bytearray"] | None:
        # Returns a bytearray (pickles/concatenates like bytes): the
        # C++ side writes directly into it — one copy total, same as
        # the Python store's slice.
        if self._closed:
            return None
        return self._read_into(self._key(id_bytes), offset, length)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 9)()
        if not self._closed:
            self._lib.rt_ns_stats(self._handle, out)
        return {
            "num_blobs": int(out[0]),
            "bytes": int(out[1]),
            "fetches_served": int(out[2]),
            "spilled_blobs": int(out[3]),
            "spilled_bytes": int(out[4]),
            "spills": int(out[5]),
            "restores": int(out[6]),
            "owners": int(out[7]),
            "native": True,
        }

    def close(self) -> None:
        """Mark closed WITHOUT destroying the C++ object: in-flight RPC
        handler threads may still be inside a store call, and a
        use-after-free would segfault the daemon. The allocation is
        reclaimed at process exit (stop() is immediately followed by
        daemon shutdown); orphaned spill files are purged by the next
        daemon's pid-liveness sweep."""
        self._closed = True


def purge_stale_spills(spill_dir: str) -> None:
    """Delete spill files left by crashed prior daemons (pid-prefixed
    filenames; shared by the Python and native stores)."""
    try:
        names = os.listdir(spill_dir)
    except OSError:
        return
    for name in names:
        if not name.endswith(".blob"):
            continue
        pid_part = name.split("-", 1)[0]
        if not pid_part.isdigit() or int(pid_part) == os.getpid():
            continue
        try:
            os.kill(int(pid_part), 0)
        except ProcessLookupError:
            try:
                os.unlink(os.path.join(spill_dir, name))
            except OSError:
                pass  # another sweeper won the unlink
        except OSError:
            pass  # alive but not ours (EPERM): leave it


def make_node_store(**kwargs):
    """Native store when the toolchain/library is available (the C++
    data plane is the default, like the reference's raylet store);
    Python fallback otherwise — both honor the same config knobs.

    With the managed spill tier armed (``spill_enabled``, the default)
    the Python store is used: the watermark spiller needs the
    lease-filter/shm-twin/directory integration the executor wires
    through NodeObjectStore.enable_managed_spill (the C++ store keeps
    its own internal cap-based spilling, without checksums or
    directory awareness). ``spill_enabled=0`` restores the legacy
    native-first selection byte-identically."""
    from ray_tpu._private import spill_manager
    from ray_tpu._private.config import GLOBAL_CONFIG

    if bool(GLOBAL_CONFIG.node_store_native) and not spill_manager.SPILL_ON:
        from ray_tpu._native import load

        lib = load()
        if lib is not None:
            try:
                return NativeNodeObjectStore(lib, **kwargs)
            except Exception:  # noqa: BLE001 — fall back to Python
                pass
    from ray_tpu._private.node_executor import NodeObjectStore

    return NodeObjectStore(**kwargs)
