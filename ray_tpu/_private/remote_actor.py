"""Driver-side handle runtime for actors hosted on worker-node daemons.

TPU-native analogue of the reference's remote-actor machinery: the GCS
actor scheduler picks a node and pushes the creation task to a leased
worker there (reference: src/ray/gcs/gcs_server/gcs_actor_scheduler.h,
src/ray/core_worker/core_worker.cc:2069 CreateActor); method calls are
pushed directly to that worker with per-caller ordering (reference:
transport/direct_actor_task_submitter.h, sequential_actor_submit_
queue.h); on node death the GCS reschedules the actor onto a survivor
while restarts remain (reference: gcs_actor_manager.h max_restarts).

``RemoteActor`` mirrors LocalActor/ProcessActor's interface
(submit/kill/is_dead/wait_started) so the Runtime treats all three
uniformly. The actor's process is spawned by the daemon
(node_executor.create_actor) and lives in the daemon's process tree;
this class owns placement, restarts, and result sealing.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from ray_tpu._private import serialization
from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.scheduler import format_traceback
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorError,
    PendingCallsLimitExceeded,
    TaskCancelledError,
)


class RemoteActor:
    """An actor executing in a dedicated process on a worker daemon."""

    # The Runtime's submit queue leaves ObjectRef args in place (waiting
    # only for them to seal); this class converts them to FetchRef
    # location hints so argument bytes flow node-to-node.
    resolves_refs = True

    def __init__(
        self,
        actor_id: ActorID,
        cls: type,
        init_args: tuple,
        init_kwargs: dict,
        runtime,
        *,
        node_id,
        handle,
        resources: dict[str, float],
        max_concurrency: int = 1,
        max_restarts: int = 0,
        max_pending_calls: int = -1,
        creation_return_id: ObjectID | None = None,
        on_death: Callable[[ActorID, str], None] | None = None,
        on_restart: Callable[[ActorID], None] | None = None,
        runtime_env: dict | None = None,
    ):
        import queue as queue_mod

        self.actor_id = actor_id
        self.node_id = node_id
        self._key = actor_id.binary()
        self._cls = cls
        self._init_args = init_args
        self._init_kwargs = init_kwargs
        self._runtime = runtime
        self._handle = handle
        self._resources = dict(resources)
        self._max_concurrency = max(1, int(max_concurrency))
        self._max_restarts = max_restarts
        self._max_pending_calls = max_pending_calls
        self._runtime_env = runtime_env
        self._on_death = on_death
        self._on_restart = on_restart
        self._creation_return_id = creation_return_id
        self._queue: queue_mod.Queue = queue_mod.Queue()
        self._lock = threading.Lock()
        self._pending = 0
        self._dead = False
        self._death_reason: str | None = None
        self._num_restarts = 0
        self._gen = 0  # bumps on every crash-handling pass (single-flight)
        self.pid: int | None = None
        self._started = threading.Event()
        # Pipelined creation: the dispatch loop opens as soon as the
        # create RPC is SENT — early calls ride the same connection
        # tagged awaiting_create and the daemon orders them behind the
        # constructor. _create_acked flips once the create reply
        # landed; _create_settled resolves either way.
        self._create_acked = False
        self._create_settled = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"ray_tpu-ractor-{cls.__name__}")
        self._thread.start()

    # Interface shared with LocalActor/ProcessActor ------------------------

    def submit(self, call) -> None:
        with self._lock:
            if self._dead:
                self._fail_call(call, ActorDiedError(
                    self.actor_id, self._death_reason or "actor has died"))
                return
            if 0 <= self._max_pending_calls <= self._pending:
                self._fail_call(call, PendingCallsLimitExceeded(
                    f"actor {self._cls.__name__} has {self._pending} "
                    f"pending calls"))
                return
            self._pending += 1
            self._queue.put(call)

    def kill(self, reason: str = "killed via kill()",
             no_restart: bool = True) -> None:
        with self._lock:
            if self._dead:
                return
            gen = self._gen
            handle = self._handle
        self._kill_remote_copy(handle)
        if not no_restart:
            # Consumes a restart (or dies); off-thread — relocation can
            # block and kill() must return promptly.
            threading.Thread(
                target=self._handle_crash, args=(gen, reason),
                daemon=True).start()
        else:
            self._mark_dead(reason)

    def is_dead(self) -> bool:
        with self._lock:
            return self._dead

    def wait_started(self, timeout: float | None = None) -> bool:
        return self._started.wait(timeout)

    def notify_node_death(self, node_id) -> None:
        """The daemon hosting this actor died: restart on a survivor (or
        die permanently) even with no call in flight. Runs off-thread —
        the caller is the health monitor and relocation can block."""
        with self._lock:
            if self._dead or node_id != self.node_id:
                return
            gen = self._gen
        threading.Thread(
            target=self._handle_crash,
            args=(gen, f"node {node_id.hex()[:8]} died"),
            daemon=True,
            name=f"ray_tpu-ractor-restart-{self._cls.__name__}").start()

    # Internals ------------------------------------------------------------

    def _fail_call(self, call, error: BaseException) -> None:
        for rid in call.return_ids:
            self._runtime.store.put_error(rid, error)

    def _kill_remote_copy(self, handle) -> None:
        """Best-effort reap of this actor's process on ``handle``'s
        daemon (idempotent; the daemon may not host it)."""
        try:
            handle._control.call("actor_kill", self._key)
        except Exception:  # noqa: BLE001 — daemon gone
            pass

    def _run(self) -> None:
        try:
            # Cache hit: create_actor's serializability probe already
            # exported this class through _function_blob.
            self._cls_blob = self._runtime._function_blob(self._cls)[1]
            init_blob = self._runtime._convert_remote_args(
                self._init_args, self._init_kwargs)
        except BaseException as exc:  # noqa: BLE001 — not remotable
            self._mark_dead(f"constructor args not serializable: {exc!r}")
            if self._creation_return_id is not None:
                self._runtime.store.put_error(
                    self._creation_return_id,
                    ActorError(exc, format_traceback(exc),
                               f"{self._cls.__name__}.__init__"))
            return
        # Pipelined __init__: creation resolves on its own thread while
        # THIS thread starts dispatching queued calls immediately —
        # the first method call ships right behind the create frame
        # and the daemon runs constructor + call back-to-back with no
        # driver round trip between them.
        threading.Thread(
            target=self._create_async, args=(init_blob,), daemon=True,
            name=f"ray_tpu-ractor-create-{self._cls.__name__}").start()
        if self._max_concurrency > 1:
            self._run_concurrent()
        else:
            self._run_sequential()

    def _create_async(self, init_blob: bytes) -> None:
        try:
            err = self._create_on_cluster(init_blob)
            if err == "dead":
                # kill() raced creation; _mark_dead already ran there.
                if self._creation_return_id is not None:
                    self._runtime.store.put_error(
                        self._creation_return_id, ActorDiedError(
                            self.actor_id,
                            self._death_reason
                            or "killed during creation"))
                return
            if err is not None:
                self._mark_dead(f"constructor failed: {err!r}")
                if self._creation_return_id is not None:
                    self._runtime.store.put_error(
                        self._creation_return_id, err)
                return
            if self._creation_return_id is not None:
                self._runtime.store.put(self._creation_return_id, None)
            self._create_acked = True
            self._started.set()
        finally:
            self._create_settled.set()

    def _create_on_cluster(self, init_blob: bytes,
                           timeout: float = 300.0):
        """Create the instance on the currently-leased node, relocating
        on busy/unreachable daemons. Returns None on success or the
        creation error."""
        import os
        import sys

        from ray_tpu._private.rpc import RpcError, RpcMethodError

        deadline = time.monotonic() + timeout
        client_addr = self._runtime._client_server_addr() or None
        while True:
            with self._lock:
                if self._dead:
                    # kill() raced the creation; stop without touching
                    # ledgers twice (the abort path below cleans up).
                    return "dead"
                handle = self._handle
                node_id = self.node_id
            node_dead = False
            handle.ensure_sys_path()
            try:
                # Coalesced: actor-creation storms (ramped waves) batch
                # per destination daemon instead of a frame per actor.
                reply = handle.pool.call(
                    "create_actor", self._key, self._cls_blob, init_blob,
                    self._runtime_env, self._max_concurrency,
                    self._resources, client_addr,
                    [p for p in sys.path if p and os.path.isdir(p)],
                    coalesce=True)
            except RpcMethodError as exc:
                return ActorError(exc.cause, exc.remote_tb,
                                  f"{self._cls.__name__}.__init__")
            except (RpcError, OSError):
                if not handle.ping():
                    self._runtime._drop_remote_node(node_id)
                    node_dead = True
                else:
                    # Reply lost after send: the daemon may have created
                    # (or still be constructing) a copy. Reap it before
                    # relocating, or the copy is orphaned holding its
                    # admission reservation (and a stateful actor would
                    # split brain).
                    self._kill_remote_copy(handle)
                reply = ("busy",)
            if reply[0] == "ok":
                self.pid = reply[1]
                record = getattr(self, "_gcs_record", None)
                if record is not None:
                    # Shared lock + fresh attribute reads: can't be
                    # overwritten by a creation thread holding stale
                    # pre-relocation state (and vice versa).
                    self._runtime._record_actor_placement(
                        record, self, self.node_id)
                with self._lock:
                    raced_kill = self._dead
                if raced_kill:
                    # kill() landed between the RPC and here: reap the
                    # fresh copy and give back the re-acquired lease.
                    self._kill_remote_copy(handle)
                    self._runtime._release_actor_lease(self.actor_id)
                    return "dead"
                return None
            if reply[0] == "err":
                exc, tb = serialization.deserialize_from_buffer(
                    memoryview(reply[1]))
                return ActorError(exc, tb,
                                  f"{self._cls.__name__}.__init__")
            # busy (or unreachable): move the lease — possibly back to
            # the same node once its capacity frees. Never attempt a
            # create without holding a lease (the ledger must reflect
            # where the actor actually runs).
            placed = None
            while placed is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return TimeoutError(
                        f"could not place actor {self._cls.__name__} "
                        f"({self._resources}) on any worker daemon "
                        f"within {timeout:.0f}s")
                placed = self._runtime._relocate_actor_lease(
                    self.actor_id, self._resources,
                    exclude={node_id} if node_dead else None,
                    timeout=min(remaining, 30.0))
            if placed == "pg_dead":
                return ActorDiedError(
                    self.actor_id,
                    "placement-group bundle no longer available; the "
                    "gang must be re-formed")
            with self._lock:
                self.node_id, self._handle = placed
            time.sleep(0.05)  # saturated cluster: poll, don't hammer

    def _run_sequential(self) -> None:
        while True:
            call = self._queue.get()
            if call is None:
                return
            self._dispatch_call(call)
            # Unbind before re-blocking: a stale frame local would keep
            # the last call's args (and any nested ObjectRefs) alive.
            call = None

    def _run_concurrent(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
                max_workers=self._max_concurrency,
                thread_name_prefix=f"ractor-{self._cls.__name__}") as pool:
            while True:
                call = self._queue.get()
                if call is None:
                    return
                pool.submit(self._dispatch_call, call)
                call = None  # don't retain across the blocking get

    def _dispatch_call(self, call) -> None:
        from ray_tpu._private.rpc import RpcError, RpcMethodError

        with self._lock:
            self._pending = max(0, self._pending - 1)
            if self._dead:
                self._fail_call(call, ActorDiedError(
                    self.actor_id, self._death_reason or "actor died"))
                return
            gen = self._gen
            handle = self._handle
            node_id = self.node_id
        if getattr(call, "cancelled", False):
            self._fail_call(call, TaskCancelledError())
            return
        from ray_tpu._private.actor_runtime import _call_deadline_error

        expired = _call_deadline_error(call, self._cls.__name__)
        if expired is not None:
            # Budget died in the submit queue: typed refusal, the RPC
            # is never issued.
            self._fail_call(call, expired)
            return
        site = f"{self._cls.__name__}.{call.method_name}"
        try:
            args_blob = self._runtime._convert_remote_args(
                call.args, call.kwargs)
        except BaseException as exc:  # noqa: BLE001 — unpicklable args
            self._fail_call(call, ActorError(
                exc, "", f"{site} (argument serialization)"))
            return
        # Calls dispatched before the create reply landed are tagged:
        # the daemon holds them for the in-flight constructor instead
        # of bouncing "gone" (pipelined __init__ + first call).
        pre_ack = not self._create_acked
        try:
            reply = handle.pool.call(
                "actor_call", self._key, call.method_name, args_blob,
                len(call.return_ids),
                [r.binary() for r in call.return_ids], pre_ack,
                coalesce=True)
        except RpcMethodError as exc:
            self._fail_call(call, ActorError(exc.cause, exc.remote_tb, site))
            return
        except (RpcError, OSError) as exc:
            if handle.ping():
                # One reset socket on a healthy daemon must not destroy
                # the actor (mirror of the task path's dead-vs-transient
                # distinction): fail only this call — it may or may not
                # have executed, which the caller must treat like any
                # in-flight loss.
                self._fail_call(call, ActorError(
                    exc, "", f"{site} (transport failure; actor alive)"))
                return
            self._fail_call(call, ActorDiedError(
                self.actor_id,
                f"node {node_id.hex()[:8]} unreachable: {exc}"))
            self._handle_crash(gen, f"node unreachable: {exc}")
            return
        if reply[0] == "ok":
            try:
                self._runtime._seal_remote_results(
                    call.return_ids, reply[1], node_id, handle.address)
            except BaseException as exc:  # noqa: BLE001 — result unpicklable
                self._fail_call(call, ActorError(
                    exc, getattr(exc, "__ray_tpu_remote_tb__", "") or "",
                    site))
        elif reply[0] == "err":
            exc, tb = serialization.deserialize_from_buffer(
                memoryview(reply[1]))
            self._fail_call(call, ActorError(exc, tb, site))
        else:  # ("dead", blob) | ("gone",)
            if reply[0] == "gone" and pre_ack \
                    and not getattr(call, "_gone_retry", False):
                # The pipelined call raced a creation that relocated
                # (busy daemon): wait for creation to settle, then
                # re-dispatch once on the final handle. Never a crash —
                # the actor was not lost, it was never there.
                call._gone_retry = True
                self._create_settled.wait(timeout=600.0)
                with self._lock:
                    self._pending += 1  # re-dispatch re-decrements
                self._dispatch_call(call)
                return
            reason = "actor process died"
            if reply[0] == "gone":
                reason = "hosting daemon lost the actor (restarted?)"
            self._fail_call(call, ActorDiedError(self.actor_id, reason))
            self._handle_crash(gen, reason)

    def _handle_crash(self, gen: int, reason: str) -> None:
        """Single-flight restart-or-die (reference: GcsActorManager
        restart path — the owner reschedules while max_restarts
        allows)."""
        with self._lock:
            if self._dead or gen != self._gen:
                return  # another thread already handled this failure
            self._gen += 1
            restartable = self._num_restarts < self._max_restarts
            if restartable:
                self._num_restarts += 1
            handle = self._handle
            node_id = self.node_id
        if not restartable:
            self._mark_dead(reason)
            return
        exclude = None
        if not handle.ping():
            self._runtime._drop_remote_node(node_id)
            exclude = {node_id}
        else:
            # The old daemon is alive: kill its copy of the actor before
            # recreating elsewhere, or the process is orphaned, its
            # admission reservation leaks, and a stateful actor splits
            # brain.
            self._kill_remote_copy(handle)
        from ray_tpu._private.config import GLOBAL_CONFIG

        placed = self._runtime._relocate_actor_lease(
            self.actor_id, self._resources, exclude=exclude,
            timeout=float(GLOBAL_CONFIG.actor_restart_relocate_timeout_s))
        if placed is None or placed == "pg_dead":
            self._mark_dead(
                f"no surviving worker daemon to restart on ({reason})")
            return
        with self._lock:
            self.node_id, self._handle = placed
        try:
            init_blob = self._runtime._convert_remote_args(
                self._init_args, self._init_kwargs)
            err = self._create_on_cluster(
                init_blob,
                timeout=float(GLOBAL_CONFIG.actor_restart_relocate_timeout_s))
        except BaseException as exc:  # noqa: BLE001
            err = exc
        if err == "dead":
            return  # kill() raced the restart; already cleaned up
        if err is not None:
            self._mark_dead(f"restart failed: {err!r}")
            return
        if self._on_restart is not None:
            self._on_restart(self.actor_id)

    def _mark_dead(self, reason: str, notify: bool = True) -> None:
        import queue as queue_mod

        with self._lock:
            if self._dead:
                return
            self._dead = True
            self._death_reason = reason
            drained = []
            try:
                while True:
                    item = self._queue.get_nowait()
                    if item is not None:
                        drained.append(item)
            except queue_mod.Empty:
                pass
            self._pending = 0
        self._queue.put(None)  # wake the drain loop
        for call in drained:
            self._fail_call(call, ActorDiedError(self.actor_id, reason))
        self._started.set()  # never leave waiters hanging
        self._create_settled.set()
        if notify and self._on_death is not None:
            self._on_death(self.actor_id, reason)
