"""Task specification.

Reference: src/ray/common/task/task_spec.h:247 (TaskSpecification over
common.proto TaskSpec) — function descriptor, args, resource demand,
num_returns, retry policy, scheduling strategy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu._private.ids import ActorID, ObjectID, TaskID


def normalize_resources(
    num_cpus: float | None,
    num_tpus: float | None,
    resources: dict[str, float] | None,
    default_cpus: float = 1.0,
) -> dict[str, float]:
    """Build the resource demand map. TPU is a first-class resource here
    (the reference bolts it on via python/ray/_private/accelerators/tpu.py)."""
    demand: dict[str, float] = {}
    demand["CPU"] = float(num_cpus) if num_cpus is not None else default_cpus
    if num_tpus:
        demand["TPU"] = float(num_tpus)
    if resources:
        for key, value in resources.items():
            if key in ("CPU", "TPU"):
                demand[key] = float(value)
            else:
                demand[key] = float(value)
    return {k: v for k, v in demand.items() if v > 0}


@dataclass
class SchedulingStrategy:
    """Reference: python/ray/util/scheduling_strategies.py."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | PLACEMENT_GROUP | NODE_AFFINITY
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    node_id: str | None = None
    soft: bool = False


@dataclass
class TaskSpec:
    task_id: TaskID
    name: str
    func: Callable | None
    args: tuple
    kwargs: dict
    num_returns: int = 1
    resources: dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool | list[type] = False
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    return_ids: list[ObjectID] = field(default_factory=list)
    # Actor tasks.
    actor_id: ActorID | None = None
    is_actor_creation: bool = False
    runtime_env: dict | None = None
    # Absolute end-to-end deadline (driver wall clock, time.time());
    # None = no budget. Stamped at .remote() and carried through the
    # submit ring, dispatcher claim, execute_task_batch entries and
    # worker pipe frames — every stage checks it before doing work and
    # seals TaskTimeoutError instead of executing dead work.
    deadline: float | None = None
    # Internal bookkeeping.
    attempt: int = 0

    @property
    def is_actor_task(self) -> bool:
        return self.actor_id is not None and not self.is_actor_creation
