"""Durable GCS state — checksummed snapshots + an append-only WAL.

TPU-native analogue of the reference's GCS fault-tolerance storage
(reference: src/ray/gcs/store_client/redis_store_client.h:33 — the GCS
keeps its tables in replicated Redis so a restarted gcs_server
rehydrates). Here the head persists to the session dir with the same
framing discipline as the spill tier (spill_manager.py "RTS1"): every
byte that will be read back is length- and CRC32-guarded, so a crash
can tear a file but can never serve garbage.

Two artifacts, one recovery contract:

- **Snapshot** (``RGS1``): the full control-plane hot set — KV, jobs,
  node table, actor registry, object directory (incl. spilled-location
  marks), placement groups — pickled behind a 16-byte
  magic+length+CRC32 header, written tmp-then-rename with the previous
  good snapshot rotated to ``<path>.prev``. A torn snapshot (crash or
  ``gcs.torn_snapshot`` chaos) fails its CRC on restore and the reader
  falls back to ``.prev`` — reject-don't-crash, never silent garbage.
- **WAL** (``RGW1``): between snapshots every table mutation appends
  one ``(seq, op)`` record framed magic+seq+length+CRC32. Records are
  state-bearing upserts (full record values, absolute counters — never
  increments), so replay is idempotent; the snapshot stores the seq it
  covers (``wal_seq``) and restore applies only records with
  ``seq > wal_seq`` — effects-exactly-once even across the
  snapshot/rotate race. A torn tail (head SIGKILLed mid-append, or
  ``gcs.torn_wal`` chaos) is detected by the frame check, truncated in
  place, and counted — everything before the tear replays.

Rotation: after a snapshot commits, the live WAL rotates to
``<wal>.prev`` and a fresh one opens. Restore therefore reads: current
snapshot (else ``.prev`` snapshot), then ``wal.prev`` then ``wal``,
seq-gated — the torn-snapshot fallback keeps the records that span the
previous generation.

Disarmed (``gcs_persistence=0``), none of this is constructed and the
head keeps its legacy ``{kv, jobs}`` raw-pickle snapshot byte-
identically (gcs_server.py keeps that path verbatim).
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import zlib

_SNAP_MAGIC = b"RGS1"
_SNAP_HEADER = struct.Struct("<4sQI")       # magic, payload len, crc32
_WAL_MAGIC = b"RGW1"
_WAL_HEADER = struct.Struct("<4sQQI")       # magic, seq, payload len, crc32


class TornSnapshotError(Exception):
    """A snapshot file failed its magic/length/CRC check: the bytes on
    disk are NOT the control-plane state. The caller must fall back to
    the previous good snapshot (+ WAL), never load the payload."""


class LegacySnapshotError(Exception):
    """The file predates the framed format (a raw-pickle ``{kv, jobs}``
    snapshot from a pre-WAL head): the caller may try the legacy
    loader."""


class ReshardError(Exception):
    """The persisted GCS layout on disk was written under a different
    ``gcs_shards`` count than the one configured now. The stable
    router's ring changed, so loading these segments would silently
    misroute restored entries — refuse typed at restore instead.
    Recovery: restart with the recorded count (then drain), or point
    the head at a fresh persist path."""

    def __init__(self, recorded, configured):
        super().__init__(
            f"persisted GCS layout has gcs_shards={recorded} but "
            f"gcs_shards={configured} is configured — resharding an "
            f"existing layout is refused (would misroute restored "
            f"entries); restart with gcs_shards={recorded} or use a "
            f"fresh persist path")
        self.recorded = recorded
        self.configured = configured


# ----------------------------------------------------------------- snapshots


def write_snapshot(path: str, payload: bytes, fsync: bool = False) -> None:
    """Write ``payload`` behind the RGS1 header, tmp-then-rename, with
    the previous good snapshot rotated to ``<path>.prev`` first.

    Chaos ``gcs.torn_snapshot`` truncates the payload mid-write while
    the header still promises the full length — the crash-mid-write
    shape restore must detect and reject. OSErrors propagate: the
    caller owns the count-and-back-off policy."""
    from ray_tpu._private import chaos

    torn = (chaos.ACTIVE is not None
            and chaos.ACTIVE.should("gcs.torn_snapshot"))
    header = _SNAP_HEADER.pack(_SNAP_MAGIC, len(payload),
                               zlib.crc32(payload) & 0xFFFFFFFF)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(header)
        f.write(payload[:len(payload) // 2] if torn else payload)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    if os.path.exists(path):
        # ``.prev`` must stay an always-GOOD fallback: a torn current
        # (an earlier interrupted write) is discarded, never rotated
        # over the last good generation — two torn writes in a row
        # would otherwise leave no loadable snapshot at all.
        try:
            read_snapshot(path)
        except LegacySnapshotError:
            os.replace(path, path + ".prev")  # readable, keep it
        except (TornSnapshotError, OSError):
            try:
                os.unlink(path)
            except OSError:
                pass  # tear already handled; unlink is tidy-up
        else:
            os.replace(path, path + ".prev")
    os.replace(tmp, path)


def read_snapshot(path: str) -> bytes:
    """Read + verify one snapshot file. Raises TornSnapshotError on any
    length/CRC mismatch, LegacySnapshotError when the magic is absent
    (pre-WAL raw pickle), OSError when the file is unreadable."""
    with open(path, "rb") as f:
        header = f.read(_SNAP_HEADER.size)
        if len(header) < _SNAP_HEADER.size:
            raise TornSnapshotError(f"{path}: short header")
        magic, length, crc = _SNAP_HEADER.unpack(header)
        if magic != _SNAP_MAGIC:
            raise LegacySnapshotError(path)
        payload = f.read(length + 1)
    if len(payload) != length:
        raise TornSnapshotError(
            f"{path}: payload {len(payload)} != header {length}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise TornSnapshotError(f"{path}: CRC mismatch")
    return payload


# ----------------------------------------------------------------------- WAL


class WalWriter:
    """Append-only framed WAL. One writer per head; appends are
    serialized by the caller (the GCS table locks order the records),
    an internal lock guards the file handle across rotate()."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        self._f = open(path, "ab")

    def append(self, seq: int, payload: bytes) -> None:
        """Frame + append one record; flushes to the OS so a SIGKILL
        loses at most the in-flight append (the torn tail restore
        truncates). Chaos ``gcs.torn_wal`` writes a deliberately short
        payload under a full-length header — the deterministic
        SIGKILL-mid-append shape."""
        from ray_tpu._private import chaos

        torn = (chaos.ACTIVE is not None
                and chaos.ACTIVE.should("gcs.torn_wal"))
        header = _WAL_HEADER.pack(_WAL_MAGIC, seq, len(payload),
                                  zlib.crc32(payload) & 0xFFFFFFFF)
        with self._lock:
            self._f.write(header)
            self._f.write(payload[:len(payload) // 2] if torn else payload)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())

    def size(self) -> int:
        with self._lock:
            try:
                return self._f.tell()
            except (OSError, ValueError):
                return 0

    def rotate(self) -> None:
        """Close the live WAL, move it to ``<path>.prev`` (replacing
        the prior generation — its records are covered by the snapshot
        that just committed), open a fresh one."""
        with self._lock:
            self._f.close()
            os.replace(self.path, self.path + ".prev")
            self._f = open(self.path, "ab")

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass  # WAL handle already torn down


def replay_wal(path: str, min_seq: int, apply_fn) -> dict:
    """Read ``path`` sequentially, calling ``apply_fn(op)`` for each
    record whose ``seq > min_seq`` (op = the unpickled payload).

    Any framing violation — short header, bad magic, short payload,
    CRC mismatch — is a torn tail: the file is truncated in place at
    the last good record boundary and replay stops (everything before
    the tear was applied). Returns counters:
    ``{replayed, skipped, truncated, last_seq}``."""
    stats = {"replayed": 0, "skipped": 0, "truncated": 0,
             "last_seq": min_seq}
    try:
        f = open(path, "r+b")
    except OSError:
        return stats
    with f:
        good_end = 0
        while True:
            header = f.read(_WAL_HEADER.size)
            if not header:
                break  # clean end
            if len(header) < _WAL_HEADER.size:
                stats["truncated"] = 1
                break
            try:
                magic, seq, length, crc = _WAL_HEADER.unpack(header)
            except struct.error:
                stats["truncated"] = 1
                break
            if magic != _WAL_MAGIC:
                stats["truncated"] = 1
                break
            payload = f.read(length)
            if len(payload) != length \
                    or zlib.crc32(payload) & 0xFFFFFFFF != crc:
                stats["truncated"] = 1
                break
            try:
                op = pickle.loads(payload)
            except Exception:  # noqa: BLE001 — undecodable = torn
                stats["truncated"] = 1
                break
            good_end = f.tell()
            if seq <= min_seq:
                stats["skipped"] += 1
                continue
            apply_fn(op)
            stats["replayed"] += 1
            stats["last_seq"] = max(stats["last_seq"], seq)
        if stats["truncated"]:
            try:
                f.truncate(good_end)
            except OSError:
                pass  # RO fs: replay still proceeded
    return stats


# --------------------------------------------------------------------- epoch


def mint_epoch(path: str) -> int:
    """Read the persisted incarnation number, bump it, persist the bump
    (tmp+rename) and return it. Every head START gets a fresh epoch —
    the fencing token a lingering previous incarnation (or a daemon
    partitioned across the restart) can never present."""
    prior = 0
    try:
        with open(path) as f:
            prior = int(f.read().strip() or 0)
    except (OSError, ValueError):
        prior = 0
    epoch = prior + 1
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(epoch))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return epoch
