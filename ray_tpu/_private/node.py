"""Node agents + head/worker daemon entrypoints.

Reference: python/ray/_private/node.py (Node starts/owns the per-node
services) and src/ray/raylet (the node agent registering with the GCS
and heartbeating). A ray_tpu cluster is:

- ONE head daemon: GcsServer (RPC control plane) + its own node record;
- N worker daemons: NodeAgent registering resources + heartbeating.

Daemons are started by the CLI (``python -m ray_tpu start``) as
detached subprocesses with pidfiles under /tmp/ray_tpu (reference:
``ray start`` spawning raylet/gcs_server with session dirs).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time

from ray_tpu._private import chaos
from ray_tpu._private.rpc import (  # noqa: F401 — RpcClient re-exported for callers
    MuxRpcClient,
    RpcClient,
    RpcError,
    RpcMethodError,
    call_with_retry,
)

SESSION_DIR = os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")


def _own_address() -> str:
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def daemon_child_env(extra: dict | None = None) -> dict:
    """Environment for spawning a ray_tpu daemon subprocess: this
    checkout resolves on PYTHONPATH even when the package isn't
    installed, and TPU detection is skipped unless the caller opts in.
    Shared by every daemon spawn site (cluster_utils, the autoscaler
    provider, the YAML launcher)."""
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    prior = env.get("PYTHONPATH", "")
    if pkg_root not in prior.split(os.pathsep):
        env["PYTHONPATH"] = (
            pkg_root + (os.pathsep + prior if prior else ""))
    env.setdefault("RAY_TPU_SKIP_TPU_DETECTION", "1")
    env.update(extra or {})
    return env


class NodeAgent:
    """Registers this node with the head GCS and heartbeats.

    Reference: the raylet's NodeManager registration + ReportHeartbeat
    loop, plus the ray_syncer's push-on-change semantics
    (ray_syncer.h:88): ``poke()`` wakes the loop immediately when the
    executor's load changes, so the head's resource view is event-fresh
    instead of lagging up to a full heartbeat period."""

    def __init__(self, gcs_address: str, resources: dict,
                 labels: dict | None = None,
                 heartbeat_period_s: float = 1.0,
                 usage_fn=None, executor_address: str = "",
                 coalesce_s: float = 0.05, stats_fn=None):
        # Pipelined client: a heartbeat never queues behind a slow
        # re-register (or any other in-flight call) on the same socket,
        # and a dead head is detected by the reader thread the moment
        # the connection drops instead of on the next call's timeout.
        self.client = MuxRpcClient(gcs_address, timeout_s=30.0)
        self.resources = dict(resources)
        self.labels = dict(labels or {})
        self.heartbeat_period_s = heartbeat_period_s
        # Floor between consecutive pushes: a burst of admissions
        # coalesces into one update instead of an RPC per task.
        self.coalesce_s = coalesce_s
        # Optional live-usage callable: () -> {resource: available}
        # piggybacked on heartbeats (ray_syncer-lite).
        self.usage_fn = usage_fn
        # Optional executor-stats callable: () -> dict, piggybacked on
        # heartbeats into the GCS node-stats aggregation table (the
        # per-node /metrics series — no extra RPC, the heartbeat IS the
        # stats channel).
        self.stats_fn = stats_fn
        self.executor_address = executor_address
        self._address = f"{_own_address()}:{os.getpid()}"
        self.node_id: bytes = b""
        # Epoch fencing: the head stamps its incarnation number on
        # every reply (rpc reply metadata). ``gcs_epoch`` is the epoch
        # this agent REGISTERED under and stamps on its heartbeats;
        # ``_seen_epoch`` is the latest observed — a mismatch means
        # the head restarted and this agent must re-register before
        # its writes are accepted again (StaleEpochError fences them
        # meanwhile). None end to end against a fencing-disarmed head.
        self.gcs_epoch: "int | None" = None
        self._seen_epoch: "int | None" = None
        self._epoch_stale = threading.Event()
        self.client.on_reply_meta = self._on_reply_meta
        self.node_id = self._register()
        self._shutdown = threading.Event()
        self._poke = threading.Event()
        self._thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="node-heartbeat")
        self._thread.start()

    def _register(self) -> bytes:
        # prior_id: across a head restart the daemon asks to keep its
        # node id, so drivers' mirrored node tables (and in-flight work
        # keyed by the id) converge without a spurious death+rejoin.
        # Registration is idempotent under prior_id (the head grants
        # the same id back on a retried request), so it rides the
        # shared retry policy — a dropped frame must not cost the node
        # a death verdict.
        from ray_tpu._private.same_host import host_identity

        node_id = call_with_retry(
            self.client.call,
            "register_node", self._address, self.resources, self.labels,
            self.executor_address, prior_id=self.node_id or None,
            host_id=host_identity())
        # The register reply's metadata carried the head's current
        # epoch (observed by _on_reply_meta before the call resolved):
        # registration IS the re-sync, subsequent writes stamp it.
        self.gcs_epoch = self._seen_epoch
        self._epoch_stale.clear()
        return node_id

    def _on_reply_meta(self, meta: dict) -> None:
        """Reader-thread observer for the head's reply metadata: an
        epoch differing from the one we registered under means the
        head restarted — wake the loop to re-register (its next
        stamped write would be fenced anyway)."""
        epoch = meta.get("epoch") if isinstance(meta, dict) else None
        if not isinstance(epoch, int):
            return
        self._seen_epoch = epoch
        if self.gcs_epoch is not None and epoch != self.gcs_epoch \
                and not self._epoch_stale.is_set():
            from ray_tpu._private import flight_recorder

            flight_recorder.record("epoch.bump", self.gcs_epoch, epoch)
            self._epoch_stale.set()
            self._poke.set()

    def poke(self) -> None:
        """Load changed: push a heartbeat now (coalesced)."""
        self._poke.set()

    def _heartbeat_loop(self) -> None:
        while not self._shutdown.is_set():
            # Wake early on poke; always wake by the heartbeat period
            # (liveness at the head depends on the periodic floor).
            self._poke.wait(self.heartbeat_period_s)
            self._poke.clear()
            if self._shutdown.is_set():
                return
            if chaos.ACTIVE is not None:
                # Chaos: a skipped beat ages this node toward the
                # head's death verdict; daemon.die is the harness's
                # in-process SIGKILL (the whole daemon vanishes the way
                # a crashed host does).
                if chaos.ACTIVE.should("daemon.die"):
                    # The one death the flusher can't race: this
                    # process is about to SIGKILL itself, so flush the
                    # flight ring synchronously — the post-mortem
                    # bundle must carry the dying daemon's last events.
                    from ray_tpu._private import flight_recorder

                    flight_recorder.dump("chaos.daemon.die")
                    os.kill(os.getpid(), signal.SIGKILL)
                if chaos.ACTIVE.should("heartbeat.skip"):
                    self._shutdown.wait(self.coalesce_s)
                    continue
            available = None
            if self.usage_fn is not None:
                try:
                    available = self.usage_fn()
                except Exception:  # noqa: BLE001 — usage is best-effort
                    available = None
            stats = None
            if self.stats_fn is not None:
                try:
                    stats = self.stats_fn()
                except Exception:  # noqa: BLE001 — stats are best-effort
                    stats = None
            trace = None
            from ray_tpu.util import tracing

            if tracing.TRACE_ON:
                # Piggyback this daemon's buffered spans (user spans,
                # orphans no reply frame carried) with a wall-clock
                # anchor for the head's one-way offset estimate.
                spans = tracing.drain_buffered()
                if spans:
                    trace = {"spans": spans, "now": time.time()}
            try:
                if self._epoch_stale.is_set():
                    # The head restarted under us (epoch bump seen on
                    # a reply): re-register BEFORE the next stamped
                    # write — the fence would reject it anyway.
                    self.node_id = self._register()
                # Heartbeats are idempotent: ride the shared retry
                # policy with a short per-try timeout so one dropped
                # frame costs a retry, not a liveness-timeout stall.
                accepted = call_with_retry(
                    self.client.call, "heartbeat", self.node_id,
                    available, stats, trace, attempts=2,
                    timeout_s=max(3.0, self.heartbeat_period_s * 3),
                    epoch=self.gcs_epoch)
                if not accepted:
                    # Unknown/dead at the head (stall past the timeout
                    # or a head restart): re-register, asking to keep
                    # our id — the head grants it unless it declared
                    # this id dead (reference: raylet re-registration
                    # after GCS restart keeps the NodeID).
                    from ray_tpu._private import flight_recorder

                    flight_recorder.record("heartbeat.rejected")
                    self.node_id = self._register()
                    flight_recorder.record("re-registered",
                                           self.node_id.hex()[:16])
            except RpcMethodError as exc:
                from ray_tpu._private.gcs import StaleEpochError

                if isinstance(exc.cause, StaleEpochError):
                    # Typed fence: this agent heartbeated with a
                    # previous incarnation's epoch (partitioned across
                    # the head restart). Re-sync by re-registering;
                    # the next beat is accepted.
                    from ray_tpu._private import flight_recorder

                    flight_recorder.record(
                        "heartbeat.stale_epoch",
                        exc.cause.current_epoch)
                    try:
                        self.node_id = self._register()
                    except (RpcError, RpcMethodError, OSError):
                        pass  # head flapped again; next beat retries
                else:
                    from ray_tpu._private import flight_recorder
                    from ray_tpu.exceptions import SystemOverloadedError

                    if isinstance(exc.cause, SystemOverloadedError):
                        # A degraded GCS shard shed this beat's
                        # piggyback typed (queue at cap). Liveness is
                        # unaffected — the next beat retries — but the
                        # shed belongs in the post-mortem ring.
                        flight_recorder.record(
                            "heartbeat.shed",
                            getattr(exc.cause, "retry_after_s", 0.0))
            except (RpcError, OSError):
                pass  # head unreachable; keep trying (it may restart)
            # Coalescing floor: pokes landing during the sleep fold
            # into the next push.
            self._shutdown.wait(self.coalesce_s)

    def stop(self, drain: bool = True) -> None:
        self._shutdown.set()
        if drain:
            try:
                self.client.call("drain_node", self.node_id)
            except (RpcError, RpcMethodError, OSError):
                pass  # drain is advisory; head may be gone
        self.client.close()


def _install_daemon_recorder(role: str, executor) -> "object":
    """Daemon-side flight recorder: flushing armed (the ring file must
    survive SIGKILL) and dumps enriched with this daemon's fault
    counters, breaker state and recent stage histograms — the
    post-mortem trio `ray_tpu debug` bundles."""
    from ray_tpu._private import flight_recorder, perf_plane
    from ray_tpu._private.rpc import breaker_stats

    def extra() -> dict:
        return {"fault_stats": executor._fault_stats(),
                "breaker": breaker_stats(),
                "spill": executor._spill_stats(),
                "stage_hist": perf_plane.stage_snapshot()}

    return flight_recorder.install(role, flush=True, extra_fn=extra)


def default_resources() -> dict:
    resources = {"CPU": float(os.cpu_count() or 1)}
    try:
        from ray_tpu._private import accelerators

        resources.update(accelerators.detect_resources())
    except Exception:  # noqa: BLE001 — detection is best-effort
        pass
    return resources


def run_head(port: int, resources: dict | None = None,
             dashboard_port: int | None = 0) -> None:
    """Head daemon: GCS server + dashboard + own node registration.
    Blocks."""
    from ray_tpu._private.gcs_server import GcsServer
    from ray_tpu.dashboard import Dashboard, gcs_provider

    os.makedirs(SESSION_DIR, exist_ok=True)
    snapshot_path = os.path.join(SESSION_DIR, "gcs_snapshot.pkl")
    # Bare ring BEFORE the GCS restores: recovery events (WAL replay,
    # torn-tail truncation, epoch mint) must land in the head's flight
    # ring; _install_daemon_recorder upgrades it with flushing later.
    from ray_tpu._private import flight_recorder

    flight_recorder.install("daemon-head")
    server = GcsServer(port=port, log_dir=SESSION_DIR,
                       persist_path=snapshot_path)
    server.start()
    dashboard = None
    if dashboard_port is not None:
        # Bind all interfaces: the advertised address file carries the
        # external IP, which must actually be reachable.
        dashboard = Dashboard(gcs_provider(server), host="0.0.0.0",
                              port=dashboard_port).start()
        with open(os.path.join(SESSION_DIR, "dashboard_address"),
                  "w") as f:
            f.write(f"{_own_address()}:{dashboard.port}")

    # Client server: remote drivers run tasks/actors against the head's
    # runtime (reference: ray client server inside `ray start --head`).
    import ray_tpu
    from ray_tpu.util.client import ClientServer

    ray_tpu.init(ignore_reinit_error=True)
    client_server = ClientServer(host="0.0.0.0", port=0).start()
    with open(os.path.join(SESSION_DIR, "client_address"), "w") as f:
        f.write(f"{_own_address()}:{client_server.port}")
    # The head's heartbeat availability reflects BOTH consumers of its
    # cores: leased executor tasks and client-server work on the
    # in-process runtime (reporting only one would double-book the
    # node in status/list_nodes).
    from ray_tpu._private.worker import global_runtime

    def head_usage():
        avail = dict(executor.available_resources())
        runtime = global_runtime()
        if runtime is not None:
            rt_avail = runtime.available_resources()
            for key, total in runtime.cluster_resources().items():
                used = total - rt_avail.get(key, 0.0)
                if used > 0:
                    avail[key] = avail.get(key, 0.0) - used
        return avail

    # The head is ALSO an executor node: connected drivers can lease
    # tasks onto it like any worker daemon (reference: `ray start
    # --head` contributes its own raylet + worker pool).
    from ray_tpu._private.node_executor import NodeExecutorService

    head_resources = resources or default_resources()
    os.environ.setdefault("RAY_TPU_NODE_TAG", f"head-{os.urandom(4).hex()}")
    from ray_tpu._private.config import GLOBAL_CONFIG

    if bool(GLOBAL_CONFIG.tracing_enabled):
        from ray_tpu.util import tracing

        tracing.enable()
    executor = NodeExecutorService(resources=head_resources)
    executor.advertised_address = executor.address_for(_own_address())
    executor.start()
    _install_daemon_recorder("daemon-head", executor)

    agent = NodeAgent(f"127.0.0.1:{server._server.port}",
                      head_resources,
                      labels={"node_role": "head"},
                      usage_fn=head_usage,
                      executor_address=executor.address_for(_own_address()),
                      stats_fn=executor.stats_for_sync)
    executor.set_load_listener(agent.poke)

    # Written LAST: `start` blocks on this file, so by the time the CLI
    # returns, the head's own node (executor included) is registered
    # and `status` immediately shows 1 alive node.
    with open(os.path.join(SESSION_DIR, "head_address"), "w") as f:
        f.write(f"{_own_address()}:{server._server.port}")

    stop_event = threading.Event()

    def on_term(signum, frame):
        stop_event.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop_event.wait(0.5):
            pass
    finally:
        agent.stop()
        executor.stop()
        client_server.stop()
        if dashboard is not None:
            dashboard.stop()
        server.stop()
        # Clean stop = session over: the snapshot/WAL exist for CRASH
        # recovery only. Leaving them would resurrect stale jobs/actors
        # into the NEXT, unrelated cluster on this machine. The epoch
        # file deliberately SURVIVES: incarnation numbers are monotonic
        # per session dir, so a daemon partitioned across sessions can
        # still never present a current-looking epoch.
        import glob as glob_mod

        # Per-shard segments (<snapshot>.shard<i>[.wal][.prev]) follow
        # the same rule; the gcs_epoch_shard<i> files survive with the
        # head's epoch file for the same fencing reason.
        for path in [snapshot_path + suffix
                     for suffix in ("", ".prev", ".wal", ".wal.prev")] \
                + glob_mod.glob(snapshot_path + ".shard*"):
            try:
                os.unlink(path)
            except OSError:
                pass  # generation file already absent


def run_worker(gcs_address: str, resources: dict | None = None,
               pool_size: int | None = None,
               labels: dict | None = None,
               heartbeat_period_s: float = 1.0) -> None:
    """Worker-node daemon: executor service + register + heartbeat.
    Blocks. (Reference: the raylet — lease-based dispatch onto this
    node's worker pool, node_manager.cc:1714.) ``labels`` merge into
    the node record (e.g. the autoscaler provider's tag)."""
    from ray_tpu._private.node_executor import NodeExecutorService

    resources = resources or default_resources()
    # Unique per-daemon tag, inherited by this node's pool workers (set
    # BEFORE the pool spawns) — tasks can read it to learn where they ran.
    os.environ["RAY_TPU_NODE_TAG"] = os.urandom(6).hex()
    from ray_tpu._private.config import GLOBAL_CONFIG

    if bool(GLOBAL_CONFIG.tracing_enabled):
        # Daemons inherit RAY_TPU_TRACING_ENABLED through the child env:
        # user spans opened inside daemon-hosted tasks collect and ship
        # on heartbeats without any driver involvement.
        from ray_tpu.util import tracing

        tracing.enable()
    executor = NodeExecutorService(
        pool_size=pool_size, resources=resources)
    executor.advertised_address = executor.address_for(_own_address())
    executor.start()
    _install_daemon_recorder(
        f"daemon-{os.environ['RAY_TPU_NODE_TAG'][:8]}", executor)
    agent = NodeAgent(gcs_address, resources,
                      labels={"node_role": "worker", **(labels or {})},
                      heartbeat_period_s=heartbeat_period_s,
                      usage_fn=executor.available_resources,
                      executor_address=executor.address_for(_own_address()),
                      stats_fn=executor.stats_for_sync)
    executor.set_load_listener(agent.poke)
    stop_event = threading.Event()

    def on_term(signum, frame):
        stop_event.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop_event.wait(0.5):
            pass
    finally:
        from ray_tpu._private import flight_recorder

        flight_recorder.record("daemon.stop")
        flight_recorder.dump("shutdown")
        agent.stop()
        executor.stop()


def main(argv: list[str]) -> None:
    role = argv[0]
    kwargs = json.loads(argv[1]) if len(argv) > 1 else {}
    if role == "head":
        run_head(**kwargs)
    elif role == "worker":
        run_worker(**kwargs)
    else:
        raise SystemExit(f"unknown node role: {role}")


if __name__ == "__main__":
    main(sys.argv[1:])
