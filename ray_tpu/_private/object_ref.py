"""ObjectRef — a handle to a (possibly pending) object.

Reference: python/ray/includes/object_ref.pxi + ownership semantics from
src/ray/core_worker/reference_count.h. A live ObjectRef contributes one
reference; deserializing a ref (e.g. inside task args) re-registers it so
borrower lifetimes are tracked.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import threading
from typing import Any

from ray_tpu._private.ids import ObjectID

_reduce_sink = threading.local()


@contextlib.contextmanager
def collect_reduced_refs(out: list):
    """Record every ObjectRef pickled on this thread into ``out``.

    Structural walks over args can't see refs nested inside custom
    objects / dataclasses / container subclasses — but pickling visits
    all of them via __reduce__. Wrapping an argument serialization in
    this collector is therefore the complete way to enumerate the refs
    a payload carries (used for the owner's grace pin while borrower
    registration is in flight)."""
    prev = getattr(_reduce_sink, "out", None)
    _reduce_sink.out = out
    try:
        yield out
    finally:
        _reduce_sink.out = prev


class ObjectRef:
    __slots__ = ("_id", "_owner", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: str = "", _register: bool = True):
        self._id = object_id
        self._owner = owner
        self._registered = False
        if _register:
            runtime = _try_runtime()
            if runtime is not None:
                runtime.reference_counter.add_ref(object_id)
                self._registered = True

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner_address(self) -> str:
        return self._owner

    # -- lifecycle ----------------------------------------------------------

    def __del__(self):
        # Runs at arbitrary GC points, possibly while this thread holds
        # runtime locks: the counter defers the real work to a reaper
        # thread (deque append is lock-free), so __del__ can never
        # deadlock against the lock its own thread already holds.
        if getattr(self, "_registered", False):
            try:
                runtime = _try_runtime()
                if runtime is not None:
                    runtime.reference_counter.defer_remove(self._id)
            except BaseException:
                pass  # interpreter teardown: runtime half-gone

    def __reduce__(self):
        # Deserializing creates a borrower reference on the receiving side.
        sink = getattr(_reduce_sink, "out", None)
        if sink is not None:
            sink.append(self)
        return (ObjectRef, (self._id, self._owner))

    # -- convenience --------------------------------------------------------

    def future(self) -> concurrent.futures.Future:
        """Return a concurrent.futures.Future resolving to the value."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        runtime = _try_runtime()
        if runtime is None:
            fut.set_exception(RuntimeError("ray_tpu is not initialized"))
            return fut
        runtime.attach_future(self, fut)
        return fut

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"


def _try_runtime():
    from ray_tpu._private import worker

    return worker.global_runtime()


def resolve_args(args: tuple, kwargs: dict, get_fn) -> tuple[tuple, dict, list[Any]]:
    """Replace top-level ObjectRef args with their values.

    Matches the reference's dependency-resolution semantics
    (src/ray/core_worker/transport/dependency_resolver.h): only top-level
    refs are resolved; refs nested inside containers are passed through
    (the callee must call get() itself).
    """
    resolved_args = tuple(get_fn(a) if isinstance(a, ObjectRef) else a for a in args)
    resolved_kwargs = {
        k: get_fn(v) if isinstance(v, ObjectRef) else v for k, v in kwargs.items()
    }
    deps = [a for a in args if isinstance(a, ObjectRef)] + [
        v for v in kwargs.values() if isinstance(v, ObjectRef)
    ]
    return resolved_args, resolved_kwargs, deps
