"""In-memory object store with spilling and reference counting.

TPU-native analogue of the reference's two-tier store: the in-process
memory store for small objects/futures (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.h) plus the
plasma shared-memory store with LRU eviction and disk spilling (reference:
src/ray/object_manager/plasma/object_store.h,
src/ray/raylet/local_object_manager.h:110 SpillObjects).

Objects here are held as live Python objects (zero-copy within the node —
host numpy/jax arrays are shared by reference, the moral equivalent of
plasma's mmap zero-copy reads). When the store exceeds its memory budget,
sealed objects with no pinned readers are spilled to disk (pickled) and
restored transparently on access.

Reference counting follows the ownership model (reference:
src/ray/core_worker/reference_count.h:61): the driver/worker that created
an object owns it; local ObjectRef lifetimes drive the count and an object
with zero references becomes evictable.
"""

from __future__ import annotations

import os
import pickle
import threading

from ray_tpu._private import lock_witness
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import (
    GetTimeoutError,
    ObjectFreedError,
    ObjectLostError,
)


def _sizeof(value: Any) -> int:
    """Best-effort deep size estimate without serializing."""
    # Exact-type fast head: scalar/str/bytes seals (the columnar
    # completion path is almost entirely these) skip the numpy/jax
    # isinstance probes below.
    t = type(value)
    if t is int or t is float or t is bool or value is None:
        return 64
    if t is bytes or t is str or t is bytearray:
        return len(value)
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return int(value.nbytes)
    except Exception:
        pass  # numpy absent/half-imported: not an ndarray
    try:
        import jax

        if isinstance(value, jax.Array):
            return int(value.size * value.dtype.itemsize)
    except Exception:
        pass  # jax absent/half-imported: not a jax.Array
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, str):
        return len(value)
    if isinstance(value, (list, tuple, set)) and len(value) < 1024:
        return 64 + sum(_sizeof(v) for v in value)
    if isinstance(value, dict) and len(value) < 1024:
        return 64 + sum(_sizeof(k) + _sizeof(v) for k, v in value.items())
    return 64


@dataclass
class ObjectEntry:
    object_id: ObjectID
    value: Any = None
    error: BaseException | None = None
    sealed: bool = False
    size_bytes: int = 0
    spilled_path: str | None = None
    freed: bool = False
    # Lost: was sealed, then its node died. Getters block until lineage
    # recovery reseals it (or an ObjectLostError is sealed in).
    lost: bool = False
    created_at: float = field(default_factory=time.monotonic)
    # Pinned while a get() is materializing it; pinned entries never spill.
    pin_count: int = 0
    # Managed spill tier (spill_manager.py): the spilled file carries
    # the length+CRC header and restores verify it (torn -> lineage).
    managed_spill: bool = False
    # LRU signal for the managed victim policy (stamped on get).
    last_used: float = field(default_factory=time.monotonic)


class _TornRestore(Exception):
    """Internal: a managed spill file failed its checksum — the entry
    was marked lost and the getter must wait for lineage recovery."""


class ObjectStore:
    """Node-local object store: seal/get/wait/free with spill-to-disk."""

    def __init__(self, memory_limit_bytes: int, spill_dir: str):
        # REENTRANT: any allocation inside a locked section can trigger
        # GC, which can run ObjectRef.__del__ → remove_ref → evict() on
        # THIS store from the same thread. A plain lock deadlocks there
        # (observed: _seal's _sizeof iterating a container whose temp
        # refs die mid-iteration).
        self._lock = lock_witness.Condition("object_store.ObjectStore")
        self._entries: dict[ObjectID, ObjectEntry] = {}
        self._memory_limit = memory_limit_bytes
        self._memory_used = 0
        self._spill_dir = spill_dir
        self._spilled_bytes_total = 0
        self._restored_bytes_total = 0
        # Callbacks fired (outside the lock) when an object is sealed.
        self._seal_listeners: list[Callable[[ObjectID], None]] = []
        # Batch-aware listeners: put_batch fires them ONCE with the
        # whole sealed group (per-id listeners still fire per object).
        self._batch_seal_listeners: list[Callable] = []
        # Seal-coalescing counters (the "seal" drain stage): how many
        # grouped seals happened and how many objects rode them.
        self.batch_seals = 0
        self.batch_sealed_objects = 0
        # Managed spill tier (spill_manager.py) — armed by the runtime
        # via enable_managed_spill; None keeps the legacy inline path.
        self._spill = None
        self._spill_min_bytes = 4096
        self._leased_fn = None
        self._on_backing_free = None
        self._on_torn = None
        # Values that failed to pickle once: never re-selected (an
        # unpicklable giant would otherwise be re-serialized per pass).
        self._unspillable: set[ObjectID] = set()

    # ------------------------------------------------------- managed spill

    def enable_managed_spill(self, spill_dir: str | None = None,
                             leased_fn=None, on_backing_free=None,
                             on_torn=None):
        """Arm the watermark-driven spill tier: sealed unpinned values
        above spill_high_watermark x the memory limit move to
        checksummed session-dir files asynchronously; restores verify
        the CRC and a torn file falls back to lineage reconstruction
        via ``on_torn(object_id)``. ``leased_fn`` yields id BYTES
        currently leased to same-host peers (never spilled);
        ``on_backing_free(object_id)`` drops the object's shm/arena
        twin after its heap copy moved to disk."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.spill_manager import SpillManager

        self._leased_fn = leased_fn
        self._on_backing_free = on_backing_free
        self._on_torn = on_torn
        self._spill_min_bytes = max(
            4096, int(GLOBAL_CONFIG.spill_min_object_kb) * 1024)
        self._spill = SpillManager(
            "driver-store", self._memory_limit,
            usage_fn=lambda: self._memory_used,
            victims_fn=self._spill_victims,
            extract_fn=self._spill_extract,
            commit_fn=self._spill_commit,
            spill_dir=spill_dir)
        return self._spill

    def _spill_victims(self, need_bytes: int) -> list:
        leased: set = set()
        if self._leased_fn is not None:
            try:
                leased = {bytes(b) for b in self._leased_fn()}
            except Exception:  # noqa: BLE001
                leased = set()
        with self._lock:
            cands = [
                (e.object_id, e.size_bytes, e.last_used)
                for e in self._entries.values()
                if e.sealed and not e.freed and e.error is None
                and e.spilled_path is None and e.pin_count == 0
                and e.size_bytes >= self._spill_min_bytes
                and e.object_id not in self._unspillable
                and e.object_id.binary() not in leased]
        # Size-ordered (largest first — fewest files free the most
        # bytes), least-recently-used as the tiebreak.
        cands.sort(key=lambda c: (-c[1], c[2]))
        out, covered = [], 0
        for oid, size, _used in cands:
            out.append(oid)
            covered += size
            if covered >= need_bytes:
                break
        return out

    def _spill_extract(self, object_id: ObjectID):
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.sealed or entry.freed \
                    or entry.error is not None or entry.pin_count > 0 \
                    or entry.spilled_path is not None:
                return None
            value = entry.value
        # Pickle OUTSIDE the lock (walks user containers; GC can run
        # arbitrary __del__s — same discipline as _sizeof in _seal).
        try:
            return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:  # noqa: BLE001 — unpicklable stays in memory
            with self._lock:
                self._unspillable.add(object_id)
            return None

    def _spill_commit(self, object_id: ObjectID, path: str,
                      size: int) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.sealed or entry.freed \
                    or entry.error is not None or entry.pin_count > 0 \
                    or entry.spilled_path is not None:
                return False
            entry.spilled_path = path
            entry.managed_spill = True
            entry.value = None
            self._memory_used -= entry.size_bytes
            self._spilled_bytes_total += entry.size_bytes
        if self._on_backing_free is not None:
            self._on_backing_free(object_id)
        return True

    def _unlink_spill(self, entry: ObjectEntry) -> None:
        """Drop an entry's spill file (free/evict/reseal pruning) —
        counted by the manager when it owns the format."""
        path, entry.spilled_path = entry.spilled_path, None
        entry.managed_spill = False
        if path is None:
            return
        if self._spill is not None:
            self._spill.delete_file(path)
            return
        try:
            os.unlink(path)
        except OSError:
            pass  # spill file already gone

    # ------------------------------------------------------------------ put

    def create_pending(self, object_id: ObjectID) -> None:
        """Register an object whose value will arrive later (a future)."""
        with self._lock:
            if object_id not in self._entries:
                self._entries[object_id] = ObjectEntry(object_id)

    def create_pending_batch(self, object_ids) -> None:
        """Register a whole submit flush's return objects under ONE
        lock pass (the pipelined submit path's analogue of
        ``put_batch`` on the seal side)."""
        with self._lock:
            entries = self._entries
            for object_id in object_ids:
                if object_id not in entries:
                    entries[object_id] = ObjectEntry(object_id)

    def put(self, object_id: ObjectID, value: Any) -> None:
        self._seal(object_id, value=value, error=None)

    def put_batch(self, items: "list[tuple[ObjectID, Any]]") -> None:
        """Seal a group of objects under ONE lock pass and notify
        batch listeners once for the whole group — the coalesced
        result-seal path for grouped task-batch completions."""
        if not items:
            return
        sizes = [_sizeof(value) for _, value in items]
        with self._lock:
            for (object_id, value), size_bytes in zip(items, sizes):
                self._seal_locked(object_id, value, None, size_bytes)
            self._lock.notify_all()
            self.batch_seals += 1
            self.batch_sealed_objects += len(items)
            listeners = list(self._seal_listeners)
            batch_listeners = list(self._batch_seal_listeners)
        ids = [object_id for object_id, _ in items]
        for cb in batch_listeners:
            cb(ids)
        for object_id in ids:
            for cb in listeners:
                cb(object_id)
        self._maybe_spill()

    def put_group(self, items: "list[tuple[ObjectID, Any]]") -> None:
        """Completion FAST path (ISSUE 15): seal a columnar reply
        group under one lock pass and fire batch listeners only — the
        per-id listener fan-out (concurrent.futures resolution) is
        skipped; the caller resolves futures itself on the rare
        occasions any are attached. Get-less tasks therefore seal with
        zero future machinery."""
        if not items:
            return
        sizes = [_sizeof(value) for _, value in items]
        with self._lock:
            for (object_id, value), size_bytes in zip(items, sizes):
                self._seal_locked(object_id, value, None, size_bytes)
            self._lock.notify_all()
            self.batch_seals += 1
            self.batch_sealed_objects += len(items)
            batch_listeners = list(self._batch_seal_listeners)
        ids = [object_id for object_id, _ in items]
        for cb in batch_listeners:
            cb(ids)
        self._maybe_spill()

    def put_error(self, object_id: ObjectID, error: BaseException) -> None:
        self._seal(object_id, value=None, error=error)

    def _seal(self, object_id: ObjectID, value: Any, error: BaseException | None):
        # Size OUTSIDE the lock: _sizeof walks user containers, which
        # can run arbitrary __del__s via GC.
        size_bytes = _sizeof(value) if error is None else 256
        with self._lock:
            self._seal_locked(object_id, value, error, size_bytes)
            self._lock.notify_all()
            listeners = list(self._seal_listeners)
            batch_listeners = list(self._batch_seal_listeners)
        for cb in batch_listeners:
            cb((object_id,))
        for cb in listeners:
            cb(object_id)
        self._maybe_spill()

    def _seal_locked(self, object_id: ObjectID, value: Any,
                     error: BaseException | None,
                     size_bytes: int) -> None:
        # Caller holds self._lock.
        entry = self._entries.get(object_id)
        if entry is None:
            entry = ObjectEntry(object_id)
            self._entries[object_id] = entry
        if entry.sealed and not entry.freed:
            # Idempotent reseal (e.g. task retry recomputed the value).
            if entry.spilled_path is not None:
                # Spilled copies already gave their bytes back; just drop
                # the stale file.
                self._unlink_spill(entry)
            else:
                self._memory_used -= entry.size_bytes
        entry.value = value
        entry.error = error
        entry.sealed = True
        entry.freed = False
        entry.lost = False
        entry.spilled_path = None
        entry.managed_spill = False
        entry.size_bytes = size_bytes
        self._memory_used += entry.size_bytes
        self._unspillable.discard(object_id)

    def add_seal_listener(self, cb: Callable[[ObjectID], None]) -> None:
        with self._lock:
            self._seal_listeners.append(cb)

    def add_batch_seal_listener(self, cb: Callable) -> None:
        """``cb(ids)`` fires once per seal GROUP (a 1-tuple for plain
        puts) — consumers scanning state per notification amortize the
        scan across a grouped batch completion."""
        with self._lock:
            self._batch_seal_listeners.append(cb)

    # ------------------------------------------------------------------ get

    def get(self, object_id: ObjectID, timeout: float | None = None) -> Any:
        """Block until the object is sealed; raise stored errors.

        A managed spill restore that finds its file TORN re-enters the
        wait loop after firing the runtime's lineage-recovery hook —
        the getter blocks until the producing task reseals the value
        (or an ObjectLostError is sealed in), never sees garbage."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                while True:
                    entry = self._entries.get(object_id)
                    if entry is not None and entry.freed:
                        raise ObjectFreedError(object_id, f"object {object_id.hex()} was freed")
                    if entry is not None and entry.sealed:
                        break
                    if entry is None:
                        # Unknown id: wait for it to appear (it may be in flight).
                        pass
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise GetTimeoutError(
                            f"get() timed out waiting for object {object_id.hex()}")
                    self._lock.wait(timeout=remaining if remaining is None else min(remaining, 1.0))
                entry.pin_count += 1
                entry.last_used = time.monotonic()
            torn = False
            try:
                value, error = self._materialize(entry)
            except _TornRestore:
                torn = True
            finally:
                with self._lock:
                    entry.pin_count -= 1
            if torn:
                # The entry was marked lost under the lock; hand the
                # loss to the runtime's recovery hook (lineage rebuild
                # or a sealed ObjectLostError) and wait for the reseal.
                if self._on_torn is not None:
                    try:
                        self._on_torn(object_id)
                    except Exception:  # noqa: BLE001
                        pass
                else:
                    # No recovery hook (standalone store): fail the
                    # waiters instead of blocking on a reseal that can
                    # never come.
                    from ray_tpu._private.object_ref import ObjectRef

                    self.put_error(object_id, ObjectLostError(
                        ObjectRef(object_id, _register=False),
                        f"object {object_id.hex()} spill file was torn "
                        f"and no lineage recovery is wired"))
                continue
            if error is not None:
                raise error
            return value

    def _materialize(self, entry: ObjectEntry):
        """Load a (possibly spilled) sealed entry. Called outside hot lock.

        Concurrent restores of the same object race benignly: each reader
        snapshots the path under the lock, and only the thread whose
        snapshot still matches performs the restore/unlink. Managed
        spill files additionally verify their length+CRC header; a
        torn file marks the entry LOST and raises _TornRestore (the
        getter fires lineage recovery and re-waits).
        """
        from ray_tpu._private.spill_manager import TornSpillError

        while True:
            with self._lock:
                path = entry.spilled_path
                managed = entry.managed_spill
            if path is None:
                return entry.value, entry.error
            if managed:
                try:
                    payload = self._spill.restore(
                        entry.object_id.binary(), path)
                except TornSpillError:
                    with self._lock:
                        if entry.spilled_path != path:
                            continue  # raced a reseal; re-check
                        entry.spilled_path = None
                        entry.managed_spill = False
                        entry.value = None
                        entry.sealed = False
                        entry.lost = True
                    raise _TornRestore() from None
                except OSError:
                    continue  # another reader restored it; re-check
                try:
                    value = pickle.loads(payload)
                except Exception as exc:  # noqa: BLE001 — poisoned pickle
                    # The CRC passed but the payload won't load (e.g. a
                    # class definition changed): same fallback as torn.
                    with self._lock:
                        if entry.spilled_path != path:
                            continue
                        entry.spilled_path = None
                        entry.managed_spill = False
                        entry.sealed = False
                        entry.lost = True
                    try:
                        os.unlink(path)
                    except OSError:
                        pass  # torn file: loss handled via _TornRestore
                    raise _TornRestore() from exc
            else:
                try:
                    with open(path, "rb") as f:
                        value = pickle.load(f)
                except FileNotFoundError:
                    continue  # another reader restored it; re-check
            with self._lock:
                if entry.spilled_path == path:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass  # restore won; file unlink is tidy-up
                    entry.spilled_path = None
                    entry.managed_spill = False
                    entry.value = value
                    self._memory_used += entry.size_bytes
                    self._restored_bytes_total += entry.size_bytes
            self._maybe_spill()
            # Return OUR loaded copy, not entry.value: a concurrent
            # reader may have restored and the async spiller re-spilled
            # (entry.value None again) between our read and the lock —
            # the bytes we verified are the object either way.
            return value, entry.error

    def mark_lost(self, object_id: ObjectID) -> bool:
        """Transition a sealed object back to pending because its node
        died (reference: plasma objects vanish with the raylet; the owner
        notices via the object directory). Returns True if it was sealed.
        """
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.sealed or entry.freed:
                return False
            if entry.pin_count > 0:
                # A get() is reading the value right now (same rule as
                # spilling): the driver-held copy survives the node.
                return False
            if entry.spilled_path is not None:
                self._unlink_spill(entry)
            else:
                self._memory_used -= entry.size_bytes
            entry.value = None
            entry.error = None
            entry.sealed = False
            entry.lost = True
            return True

    def is_lost(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry is not None and entry.lost and not entry.sealed

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry is not None and entry.sealed and not entry.freed

    def is_pending(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry is not None and not entry.sealed

    def wait(
        self,
        object_ids: list[ObjectID],
        num_returns: int,
        timeout: float | None,
    ) -> tuple[list[ObjectID], list[ObjectID]]:
        """Reference: CoreWorker::Wait (src/ray/core_worker/core_worker.cc:1627)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = [
                    oid for oid in object_ids
                    if (e := self._entries.get(oid)) is not None and e.sealed and not e.freed
                ]
                if len(ready) >= num_returns:
                    ready_set = set(ready[:num_returns])
                    # preserve input order
                    ready_ordered = [o for o in object_ids if o in ready_set]
                    not_ready = [o for o in object_ids if o not in ready_set]
                    return ready_ordered, not_ready
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    ready_set = set(ready)
                    return ([o for o in object_ids if o in ready_set],
                            [o for o in object_ids if o not in ready_set])
                self._lock.wait(timeout=remaining if remaining is None else min(remaining, 1.0))

    # ----------------------------------------------------------------- free

    def free(self, object_ids: list[ObjectID]) -> None:
        with self._lock:
            for oid in object_ids:
                entry = self._entries.get(oid)
                if entry is None:
                    continue
                if entry.sealed and entry.spilled_path is None:
                    self._memory_used -= entry.size_bytes
                if entry.spilled_path is not None:
                    self._unlink_spill(entry)
                entry.value = None
                entry.error = None
                entry.freed = True
                entry.sealed = True
                entry.spilled_path = None
                self._unspillable.discard(oid)
            self._lock.notify_all()

    def evict(self, object_id: ObjectID) -> None:
        """Drop an object entirely (refcount reached zero)."""
        with self._lock:
            entry = self._entries.pop(object_id, None)
            self._unspillable.discard(object_id)
            if entry is not None and entry.sealed and not entry.freed \
                    and entry.spilled_path is None:
                self._memory_used -= entry.size_bytes
            if entry is not None and entry.spilled_path is not None:
                self._unlink_spill(entry)

    # ----------------------------------------------------------------- spill

    def _maybe_spill(self) -> None:
        """Spill least-recently-created unpinned objects above the budget.

        Reference: LocalObjectManager::SpillObjects
        (src/ray/raylet/local_object_manager.h:110). With the managed
        tier armed, the async spiller replaces this inline pass — one
        watermark comparison here, the victim work happens off the
        seal path.
        """
        if self._spill is not None:
            self._spill.notify()
            return
        to_spill: list[ObjectEntry] = []
        with self._lock:
            if self._memory_used <= self._memory_limit:
                return
            candidates = sorted(
                (e for e in self._entries.values()
                 if e.sealed and not e.freed and e.error is None
                 and e.spilled_path is None and e.pin_count == 0
                 and e.size_bytes > 4096),
                key=lambda e: e.created_at,
            )
            need = self._memory_used - int(self._memory_limit * 0.7)
            for entry in candidates:
                if need <= 0:
                    break
                to_spill.append(entry)
                need -= entry.size_bytes
        if not to_spill:
            return
        os.makedirs(self._spill_dir, exist_ok=True)
        for entry in to_spill:
            path = os.path.join(self._spill_dir, entry.object_id.hex())
            try:
                with open(path, "wb") as f:
                    pickle.dump(entry.value, f, protocol=pickle.HIGHEST_PROTOCOL)
            except Exception:
                continue  # unpicklable objects just stay in memory
            with self._lock:
                if entry.pin_count == 0 and entry.spilled_path is None and entry.sealed:
                    entry.spilled_path = path
                    entry.value = None
                    self._memory_used -= entry.size_bytes
                    self._spilled_bytes_total += entry.size_bytes
                else:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass  # evicted copy's file already gone

    # ----------------------------------------------------------------- stats

    def snapshot(self) -> list[dict]:
        """Per-object state listing for the state API."""
        with self._lock:
            out = []
            for entry in self._entries.values():
                if entry.freed:
                    state = "FREED"
                elif entry.lost and not entry.sealed:
                    state = "LOST"
                elif entry.sealed and entry.error is not None:
                    state = "ERRORED"
                elif entry.sealed:
                    state = "SEALED"
                else:
                    state = "PENDING"
                holds_bytes = entry.sealed and not entry.freed
                out.append({
                    "object_id": entry.object_id.hex(),
                    "state": state,
                    "size_bytes": entry.size_bytes if holds_bytes else 0,
                    "spilled": entry.spilled_path is not None,
                })
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._entries),
                "num_sealed": sum(1 for e in self._entries.values() if e.sealed),
                "memory_used_bytes": self._memory_used,
                "memory_limit_bytes": self._memory_limit,
                "spilled_bytes_total": self._spilled_bytes_total,
                "restored_bytes_total": self._restored_bytes_total,
            }


class ReferenceCounter:
    """Ownership-based distributed reference counting (single-node slice).

    Reference: src/ray/core_worker/reference_count.h:61 — the owner tracks
    local refs plus borrower counts; here all refs are node-local so the
    count is the number of live ObjectRef handles plus task-argument pins.

    GC safety: ObjectRef.__del__ runs at ARBITRARY points — including
    while this thread already holds one of the runtime's locks — so the
    destructor path must be lock-free. ``defer_remove`` appends to a
    deque (GIL-atomic, no lock) and a reaper thread performs the actual
    remove_ref/evict work.
    """

    def __init__(self, store: ObjectStore):
        import collections

        self._lock = lock_witness.Lock("object_store.ReferenceCounter")
        self._counts: dict[ObjectID, int] = {}
        self._store = store
        # Optional hook fired after refcount-zero eviction (the runtime
        # drops its directory/lineage entries there).
        self.on_evict: Callable[[ObjectID], None] | None = None
        self._deferred: "collections.deque[ObjectID]" = collections.deque()
        self._reaper = threading.Thread(
            target=self._reap_loop, daemon=True, name="ray_tpu-ref-reaper")
        self._reaper.start()

    def defer_remove(self, object_id: ObjectID) -> None:
        """Destructor entry point: ONLY a deque append (GIL-atomic).
        Even Event.set() takes a lock and could deadlock a nested GC
        __del__ — the reaper polls instead of being signalled."""
        self._deferred.append(object_id)

    def _reap_loop(self) -> None:
        while True:
            try:
                object_id = self._deferred.popleft()
            except IndexError:
                time.sleep(0.02)
                continue
            try:
                self.remove_ref(object_id)
            except Exception:  # noqa: BLE001 — reaper must survive
                pass

    def add_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1

    def seed_ref(self, object_id: ObjectID) -> None:
        """Register the FIRST reference of a freshly minted id without
        the lock: no other thread can know this id yet, and a dict
        setitem is GIL-atomic — the per-call lock acquire was a
        measurable slice of the columnar submit hot path."""
        self._counts[object_id] = 1

    def remove_ref(self, object_id: ObjectID) -> None:
        evict = False
        with self._lock:
            count = self._counts.get(object_id)
            if count is None:
                return
            if count <= 1:
                del self._counts[object_id]
                evict = True
            else:
                self._counts[object_id] = count - 1
        if evict:
            self._store.evict(object_id)
            if self.on_evict is not None:
                self.on_evict(object_id)

    def count(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._counts.get(object_id, 0)

