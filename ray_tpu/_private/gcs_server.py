"""GCS server — the head node's control plane, served over RPC.

Reference: src/ray/gcs/gcs_server/gcs_server.h (GcsServer hosts the
node/actor/job/KV services over gRPC; python/ray/_private/gcs_utils.py
is the client side). Here one RpcServer exposes a
GlobalControlService's tables plus the job manager to every node,
driver, and CLI in the cluster.

Heartbeat failure detection matches the reference's
gcs_health_check_manager.h: nodes that miss heartbeats past the
threshold are marked dead and published on the node channel.
"""

from __future__ import annotations

import os
import subprocess
import threading

from ray_tpu._private import gcs_shard, lock_witness, metrics_history
import time
from typing import Any

from ray_tpu._private.gcs import (
    GlobalControlService,
    JobRecord,
    NodeRecord,
)
from ray_tpu._private.ids import JobID, NodeID
from ray_tpu._private.rpc import RpcServer

# Persistence-failure back-off window (seconds): after a failed
# snapshot/WAL write the head stops hammering the disk for this long —
# the same degrade-don't-die discipline as the spill tier's disk-full
# back-off. Durability degrades; the control plane never dies.
_PERSIST_BACKOFF_S = 5.0


class JobManager:
    """Head-side job submission (reference:
    dashboard/modules/job/job_manager.py — entrypoint subprocesses with
    captured logs and terminal-state tracking)."""

    def __init__(self, gcs: GlobalControlService, log_dir: str):
        self.gcs = gcs
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._procs: dict[str, subprocess.Popen] = {}
        self._lock = lock_witness.Lock("gcs_server.JobManager")

    def submit(self, entrypoint: str, *, submission_id: str | None = None,
               env: dict | None = None, cwd: str | None = None) -> str:
        job_id = JobID()
        sub_id = submission_id or f"raysubmit_{job_id.hex()[:12]}"
        # Idempotent on submission_id: a client retrying a dropped RPC
        # (rpc.py reconnect) must not launch the entrypoint twice. The
        # check-and-register is atomic under the lock (two server
        # threads can carry the same retried request concurrently).
        with self._lock:
            if submission_id is not None \
                    and self._record(sub_id) is not None:
                return sub_id
            self.gcs.register_job(JobRecord(
                job_id=job_id, status="RUNNING", entrypoint=entrypoint,
                submission_id=sub_id))
        log_path = os.path.join(self.log_dir, f"{sub_id}.log")
        full_env = dict(os.environ)
        # A submitted driver connects back to THIS head by default.
        full_env["RAY_TPU_JOB_SUBMISSION_ID"] = sub_id
        # Entrypoints must resolve the same ray_tpu installation as the
        # head (reference: job drivers inherit the cluster's ray).
        import ray_tpu

        pkg_file = getattr(ray_tpu, "__file__", None)
        if pkg_file:
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(pkg_file)))
            prior = full_env.get("PYTHONPATH", "")
            if pkg_root not in prior.split(os.pathsep):
                full_env["PYTHONPATH"] = (
                    pkg_root + (os.pathsep + prior if prior else ""))
        full_env.update(env or {})
        try:
            log_file = open(log_path, "wb")
            proc = subprocess.Popen(
                entrypoint, shell=True, stdout=log_file,
                stderr=subprocess.STDOUT, cwd=cwd, env=full_env,
                start_new_session=True)
        except OSError as exc:
            self.gcs.finish_job(job_id, status="FAILED")
            record = self._record(sub_id)
            if record is not None:
                record.message = str(exc)
            return sub_id
        with self._lock:
            self._procs[sub_id] = proc
        threading.Thread(target=self._wait, args=(sub_id, job_id, proc),
                         daemon=True, name=f"job-wait-{sub_id}").start()
        return sub_id

    def _wait(self, sub_id: str, job_id: JobID,
              proc: subprocess.Popen) -> None:
        rc = proc.wait()
        record = self._record(sub_id)
        if record is not None and record.status == "STOPPED":
            # User-stopped (SIGTERM): keep STOPPED, don't report FAILED.
            self.gcs.finish_job(job_id, status="STOPPED")
        else:
            self.gcs.finish_job(
                job_id, status="SUCCEEDED" if rc == 0 else "FAILED")
            if record is not None:
                record.message = f"exit code {rc}"
        with self._lock:
            self._procs.pop(sub_id, None)

    def _record(self, sub_id: str) -> JobRecord | None:
        for record in self.gcs.list_jobs():
            if record.submission_id == sub_id:
                return record
        return None

    def status(self, sub_id: str) -> dict | None:
        record = self._record(sub_id)
        if record is None:
            return None
        return {
            "submission_id": record.submission_id,
            "status": record.status,
            "entrypoint": record.entrypoint,
            "message": record.message,
            "start_time": record.start_time,
            "end_time": record.end_time,
        }

    def logs(self, sub_id: str, tail_bytes: int = 1 << 20) -> bytes:
        path = os.path.join(self.log_dir, f"{sub_id}.log")
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read()
        except FileNotFoundError:
            return b""

    def stop(self, sub_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(sub_id)
        if proc is None:
            return False
        import signal

        # STOPPED first: the exit-watcher reads this the moment the
        # SIGTERM'd process exits, and must not report FAILED.
        record = self._record(sub_id)
        if record is not None:
            record.status = "STOPPED"
            record.end_time = time.time()
        try:  # the whole session: entrypoints may spawn children
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            proc.terminate()
        return True

    def list(self) -> list[dict]:
        return [self.status(r.submission_id)
                for r in self.gcs.list_jobs() if r.submission_id]

    def shutdown(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
        for proc in procs:
            try:
                proc.terminate()
            except OSError:
                pass  # entrypoint already exited


class GcsServer:
    """RPC facade over GlobalControlService + JobManager + cluster KV."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 log_dir: str = "/tmp/ray_tpu/session",
                 heartbeat_timeout_s: float | None = None,
                 persist_path: str | None = None):
        from ray_tpu._private.config import GLOBAL_CONFIG

        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = float(
                GLOBAL_CONFIG.gcs_heartbeat_timeout_s)
        # Native (C++) storage engine for the head's tables (reference:
        # the GCS storage layer is C++, in_memory_store_client.h:31);
        # gated by the same config convention as the daemon blob store.
        kv = None
        if bool(GLOBAL_CONFIG.gcs_kv_native):
            from ray_tpu._private.gcs_kv_native import make_kv_store

            kv = make_kv_store()
        # Sharded hot tables (gcs_shard.py): arm the gate BEFORE the
        # control service constructs its table domains — node stats
        # and task events shard inside GlobalControlService, the
        # object directory shards here behind _shards.
        self._shard_count = gcs_shard.init_from_config()
        self._shards = None
        self.gcs = GlobalControlService(kv=kv)
        self.jobs = JobManager(self.gcs, os.path.join(log_dir, "jobs"))
        self.heartbeat_timeout_s = heartbeat_timeout_s
        # Fault tolerance (reference: store_client/
        # redis_store_client.h:33 — redis-backed GCS FT). Armed
        # (gcs_persistence=1, the default): the FULL control-plane hot
        # set — KV, jobs, node table, actor registry, object directory
        # incl. spilled marks, placement groups — rides a checksummed
        # snapshot plus a framed WAL (gcs_persistence.py), and the head
        # mints a persisted incarnation epoch every start that fences
        # stale writers (StaleEpochError). Disarmed: the legacy
        # {kv, jobs} raw-pickle snapshot, byte-identical to the
        # pre-WAL head, no epoch, no fencing.
        self._persist_path = persist_path
        self._persisted_version = None
        self._persist_armed = bool(persist_path) and bool(
            GLOBAL_CONFIG.gcs_persistence)
        self._fencing = self._persist_armed and bool(
            GLOBAL_CONFIG.gcs_epoch_fencing)
        self.epoch = 0
        self._base_epoch = 0
        self._wal = None
        self._wal_seq = 0
        self._persist_lock = lock_witness.Lock("gcs_server.GcsServer.persist")
        self._persist_backoff_until = 0.0
        self._last_snapshot_at = 0.0
        self._persist_stats = {
            "wal_records_written": 0, "wal_records_replayed": 0,
            "wal_replay_skipped": 0, "snapshots_written": 0,
            "snapshot_restore_ms": 0.0, "torn_wal_tails": 0,
            "torn_snapshots": 0, "persist_errors": 0,
            "fenced_writes": 0,
        }
        # Cluster object-location directory (multi-holder; pruned when
        # an owner stops refreshing its lease — its driver exited).
        # Constructed BEFORE restore so the snapshot can rehydrate it.
        from ray_tpu._private.gcs import ObjectDirectory

        self.object_directory = ObjectDirectory()
        # Head-side placement-group mirror: drivers publish their PG
        # managers' snapshots (pg_update) so the table survives a head
        # crash with the rest of the hot set.
        self._pg_table: dict[str, list] = {}
        self._pg_version = 0
        self._pg_lock = lock_witness.Lock("gcs_server.GcsServer.pg")
        if persist_path and self._persist_armed:
            import glob as glob_mod

            from ray_tpu._private import gcs_persistence as gp

            if self._shard_count == 1 \
                    and glob_mod.glob(persist_path + ".shard*"):
                # Per-shard segments on disk but a single-shard config:
                # their directory entries would be silently ignored.
                raise gp.ReshardError("2+", self._shard_count)
            self.epoch = gp.mint_epoch(os.path.join(
                os.path.dirname(persist_path) or ".", "gcs_epoch"))
            self._base_epoch = self.epoch
            self._restore_full()
            if self._shard_count > 1 and (
                    self.object_directory.locations()
                    or self.object_directory.spilled()):
                # Directory entries came out of the single-WAL layout:
                # it was written with gcs_shards=1 (a snapshot records
                # the count explicitly; a WAL-only layout shows up
                # here).
                raise gp.ReshardError(1, self._shard_count)
            try:
                self._wal = gp.WalWriter(
                    persist_path + ".wal",
                    fsync=bool(GLOBAL_CONFIG.gcs_wal_fsync))
            except OSError:
                self._count_persist_error("wal_open")
            # Every durable mutation from here on appends its op while
            # the owning table's lock is held (WAL order == apply
            # order).
            self.gcs.wal_emit = self._wal_append
            self.object_directory.wal_emit = self._wal_append
            if self._shard_count > 1:
                # Tentpole: the object directory splits across N shard
                # domains, each with its own lock domain, WAL+snapshot
                # segment and persisted incarnation epoch, so one
                # shard crash-restarts (replaying only ITS WAL) while
                # the rest keep serving.
                import re as re_mod

                seen = set()
                for seg in glob_mod.glob(persist_path + ".shard*"):
                    m = re_mod.match(r".*\.shard(\d+)", seg)
                    if m is not None:
                        seen.add(int(m.group(1)))
                if seen and seen != set(range(self._shard_count)):
                    # Segment indices disagree with the configured
                    # ring: a shrink would silently orphan entries, a
                    # growth would misroute removes — refused even for
                    # a WAL-only layout no snapshot stamped. max+1 is
                    # exact: every shard of the old ring opened its
                    # WAL at boot.
                    raise gp.ReshardError(
                        max(seen) + 1, self._shard_count)
                queue_cap = int(
                    GLOBAL_CONFIG.gcs_shard_max_queued_writes)
                self._shards = [
                    gcs_shard.ShardState(
                        i, self._shard_count, persist_path,
                        fsync=bool(GLOBAL_CONFIG.gcs_wal_fsync),
                        queue_cap=queue_cap)
                    for i in range(self._shard_count)]
                for shard in self._shards:
                    shard.on_persist_error = self._count_persist_error
                    shard.boot()
                self._refresh_epoch()
        elif persist_path:
            self._restore_snapshot()
        self._server = RpcServer(host, port)
        if self._fencing:
            # Every reply out of this server carries the incarnation
            # epoch as reply metadata — daemons and drivers detect a
            # bump on ANY call and re-register/re-publish.
            self._server.reply_meta_fn = lambda: {"epoch": self.epoch}
        self._shutdown = threading.Event()
        # Cross-process channel hub; the head's own membership events
        # bridge onto the "nodes" channel so any cluster process can
        # react by push instead of polling list_nodes.
        from ray_tpu._private.gcs_pubsub import ChannelHub

        self.pubsub = ChannelHub()
        self.gcs.pubsub.subscribe("nodes", self._on_node_event)
        # Last availability published per node (change detection for
        # the "node_resources" syncer channel).
        self._last_published_avail: dict[str, dict] = {}
        self._avail_lock = lock_witness.Lock("gcs_server.GcsServer.avail")
        # Daemon trace spans shipped on heartbeats, staged until a
        # driver drains them into its merged timeline. Bounded: a
        # cluster tracing with no driver exporting must not grow this
        # without limit.
        self._trace_spans: list[dict] = []
        self._trace_lock = lock_witness.Lock("gcs_server.GcsServer.trace")
        # Cluster history plane: fixed-interval ring store over the
        # node-stats table, sharded along the same domains as the hot
        # tables, plus the SLO watchdog sweeping it each interval.
        self._history: metrics_history.HistoryStore | None = None
        self._watchdog: metrics_history.HealthWatchdog | None = None
        if metrics_history.HISTORY_ON:
            self._history = metrics_history.HistoryStore.from_config(
                domains=max(1, self._shard_count))
            self._watchdog = metrics_history.HealthWatchdog(
                self._history)
        self._register_methods()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="gcs-monitor")

    @property
    def address(self) -> str:
        return self._server.address

    def _register_methods(self) -> None:
        s = self._server
        s.register("ping", lambda: "pong")
        # KV (reference: gcs InternalKV service). Writes WAL at the
        # RPC boundary — the one seam that covers the native (C++)
        # store, whose internals can't emit records.
        s.register("kv_put", self._kv_put)
        s.register("kv_get", self.gcs.kv.get)
        s.register("kv_del", self._kv_del)
        s.register("kv_exists", self.gcs.kv.exists)
        s.register("kv_keys", self.gcs.kv.keys)
        # Nodes.
        s.register("register_node", self._register_node)
        s.register("heartbeat", self._heartbeat)
        s.register("list_nodes", self._list_nodes)
        s.register("drain_node", self._drain_node)
        # Jobs.
        s.register("submit_job", self.jobs.submit)
        s.register("job_status", self.jobs.status)
        s.register("job_logs", self.jobs.logs)
        s.register("stop_job", self.jobs.stop)
        s.register("list_jobs", self.jobs.list)
        # Cluster-wide info.
        s.register("cluster_resources", self._cluster_resources)
        # Observability: per-node executor stats (heartbeat-pushed;
        # drivers fold them into /metrics as labeled series) and the
        # heartbeat-shipped daemon trace spans.
        s.register("node_stats", self.gcs.node_stats)
        s.register("drain_trace_spans", self._drain_trace_spans)
        # Object-location table (reference:
        # ownership_based_object_directory.h — owner -> holding nodes;
        # here owners batch-publish their primary-copy locations).
        s.register("object_locations_update",
                   self._object_locations_update)
        s.register("list_object_locations", self._list_object_locations)
        # Cluster actor registry + placement-group mirror: drivers
        # publish lifecycle upserts so the head's snapshot covers the
        # whole hot set (reference: gcs_actor_manager.h /
        # gcs_placement_group_manager.h own these tables GCS-side).
        s.register("actor_update", self._actor_update)
        s.register("list_cluster_actors", self._list_cluster_actors)
        s.register("pg_update", self._pg_update)
        s.register("list_cluster_placement_groups",
                   self._list_cluster_placement_groups)
        # Epoch fencing + persistence observability.
        s.register("gcs_epoch", lambda: self.epoch)
        s.register("gcs_persist_stats", self.persist_stats)
        # Shard plane: per-shard stats rows for /metrics, plus the
        # deterministic kill seam the soak/bench drive failover with.
        s.register("gcs_shard_stats", self.shard_stats)
        s.register("gcs_kill_shard", self._kill_shard)
        # History plane: windowed per-node rate/percentile queries over
        # the head's ring store, and the watchdog's typed verdicts.
        s.register("metrics_history", self.metrics_history)
        s.register("cluster_health", self.cluster_health)
        # Cluster-wide pub/sub channels (reference: the GCS pubsub
        # handler over src/ray/pubsub/publisher.h:307). Polls block, so
        # they dispatch concurrently like task execution does.
        s.register("pubsub_subscribe", self.pubsub.subscribe)
        s.register("pubsub_unsubscribe", self.pubsub.unsubscribe)
        s.register("pubsub_publish", self.pubsub.publish)
        s.register("pubsub_poll", self.pubsub.poll, concurrent=True)

    # -- node service -------------------------------------------------
    def _on_node_event(self, event) -> None:
        """Bridge membership events onto the cluster channel hub; a
        DEAD verdict additionally prunes the dead node from every
        object-directory holder set and publishes the objects whose
        last holder died, so owners stop being handed dead holders and
        can fire lineage reconstruction by push (reference:
        GcsNodeManager node-dead broadcast + the directory dropping the
        node's locations)."""
        kind, node_id = event
        if kind == "DEAD":
            if self._shards is not None:
                orphaned = []
                for shard in self._shards:
                    # Degraded shards queue the prune (their orphan
                    # verdicts arrive at heal through lineage's normal
                    # holder-miss path instead of this push).
                    result = self._shard_apply(
                        shard, ("dir_prune_node", node_id.hex()),
                        None, "prune_node")
                    orphaned.extend(result or [])
            else:
                orphaned = self.object_directory.prune_node(
                    node_id.hex())
            if orphaned:
                self.pubsub.publish("object_loss", orphaned)
        self.pubsub.publish("nodes", (kind, node_id.hex()))

    def _register_node(self, address: str, resources: dict,
                       labels: dict | None = None,
                       executor_address: str = "",
                       prior_id: bytes | None = None,
                       host_id: str = "") -> bytes:
        """``prior_id``: a daemon re-registering after its heartbeat was
        rejected asks to KEEP its id. Granted when this head has never
        seen the id (head restart amnesia — reference: raylets keep
        their NodeID across a GCS restart) or when the record matches
        (retry of a lost reply). Refused when the id is known DEAD: the
        death verdict stands, recovery may already be re-executing its
        lineage — the daemon comes back as a fresh node."""
        node_id = None
        if prior_id is not None:
            candidate = NodeID(prior_id)
            existing = self.gcs.get_node(candidate)
            if existing is None or (existing.alive
                                    and existing.address == address):
                node_id = candidate
        if node_id is None:
            node_id = NodeID()
        self.gcs.register_node(NodeRecord(
            node_id=node_id, address=address, resources=dict(resources),
            labels=dict(labels or {}),
            executor_address=executor_address, host_id=host_id))
        return node_id.binary()

    def _heartbeat(self, node_id_bytes: bytes,
                   available: dict | None = None,
                   stats: dict | None = None,
                   trace: dict | None = None,
                   epoch: int | None = None) -> bool:
        # Fence FIRST: a daemon partitioned across a head restart
        # presents the old incarnation's epoch — its liveness refresh
        # (and every piggyback riding it) is rejected typed instead of
        # silently refreshing a record it no longer owns. It re-syncs
        # by re-registering, then this call succeeds.
        self._check_epoch(epoch, "heartbeat")
        # False tells the agent it is unknown/dead and must re-register.
        accepted = self.gcs.heartbeat(NodeID(node_id_bytes), available)
        if accepted and stats is not None:
            # Spill-event piggyback: the daemon's spill tier reports
            # (owner, object hex, "spilled"|"restored") transitions so
            # the object directory stays spill-aware (popped — events
            # are deltas, not stats to aggregate).
            events = stats.pop("spill_events", None)
            if events:
                node_hex = node_id_bytes.hex()
                if self._shards is not None:
                    self._route_spill_events(events, node_hex, epoch)
                else:
                    for owner, obj_hex, kind in events:
                        if kind == "spilled":
                            self.object_directory.mark_spilled(
                                owner, obj_hex, node_hex)
                        else:
                            self.object_directory.clear_spilled(
                                owner, obj_hex)
            # Executor-stats piggyback: the GCS-side aggregation table
            # drivers scrape into per-node /metrics series.
            self.gcs.record_node_stats(node_id_bytes.hex(), stats)
        if accepted and trace:
            # Daemon spans piggybacked on the heartbeat. The offset is
            # a one-way estimate (recv wall clock minus the daemon's
            # send stamp) — coarser than the half-RTT reply path, but
            # these spans had no driver reply to anchor on.
            spans = trace.get("spans") or []
            anchor = trace.get("now")
            offset = (time.time() - float(anchor)) if anchor else 0.0
            with self._trace_lock:
                room = 65536 - len(self._trace_spans)
                if room > 0:
                    self._trace_spans.append(
                        {"spans": spans[:room], "offset": offset,
                         "node": node_id_bytes.hex()})
        if accepted and available is not None:
            # Syncer push: availability CHANGES fan out on the
            # "node_resources" channel so drivers' schedulers track
            # other tenants' load without polling (reference: the
            # ray_syncer resource-view stream, ray_syncer.h:88).
            # Steady-state heartbeats with unchanged availability
            # publish nothing.
            hex_id = node_id_bytes.hex()
            with self._avail_lock:
                last = self._last_published_avail.get(hex_id)
                changed = last != available
                if changed:
                    self._last_published_avail[hex_id] = dict(available)
            if changed:
                self.pubsub.publish(
                    "node_resources", (hex_id, dict(available)))
        return accepted

    def _list_nodes(self) -> list[dict]:
        return [{
            "node_id": r.node_id.hex(),
            "address": r.address,
            "resources": dict(r.resources),
            "available": dict(r.available),
            "labels": dict(r.labels),
            "executor_address": r.executor_address,
            "host_id": r.host_id,
            "alive": r.alive,
        } for r in self.gcs.list_nodes()]

    def _drain_node(self, node_id_bytes: bytes) -> bool:
        self.gcs.mark_node_dead(NodeID(node_id_bytes))
        self.gcs.drop_node_stats(node_id_bytes.hex())
        return True

    def _drain_trace_spans(self) -> list[dict]:
        """Hand the staged heartbeat-shipped span batches to the
        draining driver (one-shot: drained batches are gone)."""
        with self._trace_lock:
            out, self._trace_spans = self._trace_spans, []
            return out

    def _object_locations_update(self, owner: str, adds: list,
                                 removes: list,
                                 epoch: int | None = None) -> int:
        """Batched owner-published location deltas; an empty update is a
        keepalive that refreshes the owner's lease on its entries. A
        stale-epoch owner (partitioned across a head restart) is
        rejected typed — it re-syncs and FULL-republishes, so an old
        incarnation's deltas can never interleave into (and corrupt)
        the restored directory."""
        if self._shards is not None:
            return self._sharded_locations_update(
                owner, adds, removes, epoch)
        self._check_epoch(epoch, "object_locations_update")
        return self.object_directory.update(owner, adds, removes)

    def _list_object_locations(self, owner: str | None = None,
                               include_spilled: bool = False):
        """Holder table, optionally paired with the spilled-location
        view (``include_spilled``): consumers like the locality scorer
        discount holders whose only copy is on disk."""
        if self._shards is not None:
            # Reads never block on a wedged domain: a stalled shard's
            # in-memory view IS the stale-marked snapshot (its queued
            # writes are unapplied), served as-is with the staleness
            # age exposed as age_s in its shard_stats row.
            locations: dict = {}
            spilled: dict = {}
            for shard in self._shards:
                locations.update(shard.directory.locations(owner))
                if include_spilled:
                    spilled.update(shard.directory.spilled(owner))
            if not include_spilled:
                return locations
            return (locations, spilled)
        locations = self.object_directory.locations(owner)
        if not include_spilled:
            return locations
        return (locations, self.object_directory.spilled(owner))

    def _prune_object_locations(self, ttl_s: float = 60.0) -> None:
        if self._shards is not None:
            for shard in self._shards:
                with shard.lock:
                    if not shard._stall_active_locked():
                        shard.directory.prune(ttl_s)
            return
        self.object_directory.prune(ttl_s)

    # -- cluster actor / placement-group mirrors ----------------------
    def _actor_update(self, records: list, epoch: int | None = None
                      ) -> int:
        """Driver-published actor lifecycle upserts (full records,
        RESTARTING state and num_restarts included). Two fences: a
        stale-epoch publisher is rejected typed, and a DEAD actor is
        never resurrected to a live state by any publish — the death
        verdict stands (upsert_actor_mirror). Returns the applied
        count."""
        self._check_epoch(epoch, "actor_update")
        applied = 0
        for plain in records:
            if self.gcs.upsert_actor_mirror(plain):
                applied += 1
        return applied

    def _list_cluster_actors(self) -> list[dict]:
        return [self.gcs._actor_plain(r) for r in self.gcs.list_actors()]

    def _pg_update(self, owner: str, records: list,
                   epoch: int | None = None) -> int:
        """Driver-published placement-group snapshot (the whole
        manager view — PGs are few, deltas aren't worth the
        bookkeeping). Keyed per owner so two drivers never clobber
        each other's groups."""
        self._check_epoch(epoch, "pg_update")
        with self._pg_lock:
            self._pg_table[owner] = list(records)
            self._pg_version += 1
            if self._wal is not None:
                self._wal_append(("pg_owner", owner, list(records)))
        return len(records)

    def _list_cluster_placement_groups(self) -> dict:
        with self._pg_lock:
            return {owner: list(records)
                    for owner, records in self._pg_table.items()}

    # -- epoch fencing ------------------------------------------------
    def _check_epoch(self, epoch: int | None, site: str,
                     shard=None) -> None:
        """Reject a write stamped with a previous incarnation's epoch.
        ``epoch=None`` (a writer that has not yet learned any epoch —
        first contact, or a fencing-disarmed cluster) passes: fencing
        exists to catch writers that KNOW a stale incarnation, not to
        lock out bootstrapping ones. ``shard``: the fence fired on a
        shard-routed write (a shard restart bumped the advertised
        epoch) — counted on that shard's row too."""
        if epoch is None or not self._fencing or epoch == self.epoch:
            return
        from ray_tpu._private import flight_recorder
        from ray_tpu._private.gcs import StaleEpochError

        with self._persist_lock:
            self._persist_stats["fenced_writes"] += 1
        if shard is not None:
            with shard.lock:
                shard.fenced_writes += 1
            flight_recorder.record("gcs.shard_fenced_write",
                                   shard.index, site, epoch)
        flight_recorder.record("gcs.fenced_write", site, epoch)
        raise StaleEpochError(self.epoch, epoch)

    # -- shard routing ------------------------------------------------
    def _refresh_epoch(self) -> None:
        # Advertised epoch = persisted head base + sum of shard epochs:
        # monotonic (every component is a persisted monotonic counter)
        # and it bumps when the head OR any one shard restarts — so
        # the existing StaleEpochError fencing and reply-meta re-sync
        # machinery cover shard failover unchanged.
        self.epoch = self._base_epoch + sum(
            shard.epoch for shard in self._shards)

    def _shard_apply(self, shard, op: tuple, epoch: int | None,
                     site: str):
        """Every shard-routed durable mutation funnels here: chaos
        (gcs.shard_die / gcs.shard_stall) draws mid-mutation, the
        epoch fence runs against the CURRENT advertised epoch (a shard
        restart just bumped it, so the in-flight stale writer is
        rejected typed), then the op applies under the shard's lock
        domain — or queues WAL-first in degraded mode."""
        from ray_tpu._private import chaos

        ctl = chaos.ACTIVE
        if ctl is not None:
            if ctl.should("gcs.shard_die"):
                shard.crash_restart("chaos")
                self.gcs.crash_shard(shard.index)
                self._refresh_epoch()
            elif ctl.should("gcs.shard_stall"):
                base = float(os.environ.get(
                    "RAY_TPU_SHARD_STALL_S", "2.0"))
                shard.stall(base * (0.5 + ctl.uniform()))
        self._check_epoch(epoch, site, shard=shard)
        with shard.lock:
            if shard._stall_active_locked():
                if op[0] == "dir_update" and not op[2] and not op[3]:
                    return None  # keepalive: nothing durable to queue
                shard.enqueue_locked(op)
                return None
            return gcs_shard.apply_dir_op(shard.directory, op)

    def _sharded_locations_update(self, owner: str, adds: list,
                                  removes: list,
                                  epoch: int | None) -> int:
        """Router: each object's delta lands on its owning domain
        (object hex -> shard — owner strings differ between the
        daemon's and the driver's view, object ids don't). An empty
        update (the owner's keepalive) refreshes the lease on EVERY
        domain; a non-empty one refreshes untouched domains' leases
        for free so entries never age out shard-by-shard."""
        shards = self._shards
        n = len(shards)
        per: list = [([], []) for _ in range(n)]
        for add in adds:
            per[gcs_shard.shard_of(add[0], n)][0].append(add)
        for obj_hex in removes:
            per[gcs_shard.shard_of(obj_hex, n)][1].append(obj_hex)
        total = 0
        for i, shard in enumerate(shards):
            s_adds, s_removes = per[i]
            if s_adds or s_removes or not (adds or removes):
                total += self._shard_apply(
                    shard, ("dir_update", owner, s_adds, s_removes),
                    epoch, "object_locations_update") or 0
            else:
                # Untouched domain: bare lease refresh — no WAL
                # record, skipped while wedged (lease TTL is far
                # longer than any stall window).
                with shard.lock:
                    if not shard._stall_active_locked():
                        shard.directory.update(owner, [], [])
        return total

    def _route_spill_events(self, events, node_hex: str,
                            epoch: int | None) -> None:
        """Heartbeat spill-mark piggybacks land on the OBJECT's owning
        shard. A degraded shard sheds past its queue cap — the marks
        are advisory locality hints, so the heartbeat (the liveness
        plane) absorbs the typed overload instead of failing."""
        from ray_tpu.exceptions import SystemOverloadedError

        shards = self._shards
        n = len(shards)
        for owner, obj_hex, kind in events:
            shard = shards[gcs_shard.shard_of(obj_hex, n)]
            op = (("dir_spill", owner, obj_hex, node_hex)
                  if kind == "spilled"
                  else ("dir_unspill", owner, obj_hex))
            try:
                self._shard_apply(shard, op, epoch, "heartbeat_spill")
            except SystemOverloadedError:
                break

    def shard_stats(self) -> list:
        """Per-shard stats rows (GCS_SHARD_STAT_KEYS plus the shard
        index), served over RPC and folded into /metrics as the
        ray_tpu_gcs_shard{shard=,key=} family. Empty when sharding is
        disarmed."""
        if self._shards is None:
            return []
        return [{**shard.stats(), "shard": shard.index}
                for shard in self._shards]

    def _kill_shard(self, index: int | None = None) -> int:
        """Deterministic shard-kill seam (the chaos soak and the
        recovery bench drive failover without a probability draw):
        crash-restart one shard domain exactly as gcs.shard_die
        would — drop its volatile slices, mint its next epoch, replay
        only its WAL. Returns records replayed; -1 when disarmed."""
        if self._shards is None:
            return -1
        shard = self._shards[int(index or 0) % len(self._shards)]
        replayed = shard.crash_restart("admin")
        self.gcs.crash_shard(shard.index)
        self._refresh_epoch()
        return replayed

    # -- history plane ------------------------------------------------
    def metrics_history(self, window_s: float | None = None,
                        node: str | None = None) -> dict:
        """Windowed per-node history query (cross-domain merge; stale
        domains ride ``degraded``). Disarmed heads answer typed
        armed=False instead of erroring, so CLIs degrade cleanly."""
        if self._history is None:
            return metrics_history.disarmed_history()
        return self._history.query(window_s=window_s, node=node)

    def cluster_health(self) -> dict:
        """The watchdog's active verdicts + recent fired ring."""
        if self._watchdog is None:
            return metrics_history.disarmed_health()
        return self._watchdog.report()

    def _history_tick(self) -> None:
        """One monitor-tick turn of the history plane: when an
        interval elapsed, delta-encode the node-stats table into the
        rings and sweep the watchdog rules over the fresh window."""
        history = self._history
        if history is None or not history.due():
            return
        try:
            node_stats = self.gcs.node_stats()
            shard_rows = self.shard_stats()
            history.sample(node_stats, shard_rows)
            if self._watchdog is not None:
                self._watchdog.sweep(node_stats, shard_rows)
        except Exception:  # noqa: BLE001 — observability must not
            pass           # take down the head's monitor loop

    # -- WAL ----------------------------------------------------------
    def _wal_append(self, op: tuple) -> None:
        """Append one durable mutation (called from the table mutators
        with their lock held — WAL order matches apply order). A
        failed append degrades, never dies: the error is counted, the
        writer backs off, and the periodic full snapshot re-covers the
        lost records."""
        import pickle

        wal = self._wal
        if wal is None:
            return
        now = time.monotonic()
        with self._persist_lock:
            if now < self._persist_backoff_until:
                return
            self._wal_seq += 1
            seq = self._wal_seq
        try:
            wal.append(seq, pickle.dumps(
                op, protocol=pickle.HIGHEST_PROTOCOL))
        except OSError:
            self._count_persist_error("wal_append")
            return
        with self._persist_lock:
            self._persist_stats["wal_records_written"] += 1

    def _apply_wal_op(self, op: tuple) -> None:
        kind = op[0]
        if kind == "kv_put":
            _, namespace, key, value = op
            self.gcs.kv.put(key, value, namespace)
        elif kind == "kv_del":
            _, namespace, key = op
            self.gcs.kv.delete(key, namespace)
        elif kind in ("actor", "node", "job"):
            self.gcs.apply_op(op)
        elif kind == "dir_update":
            _, owner, adds, removes = op
            self.object_directory.update(owner, adds, removes)
        elif kind == "dir_spill":
            _, owner, obj_hex, node_hex = op
            self.object_directory.mark_spilled(owner, obj_hex, node_hex)
        elif kind == "dir_unspill":
            _, owner, obj_hex = op
            self.object_directory.clear_spilled(owner, obj_hex)
        elif kind == "dir_prune_node":
            self.object_directory.prune_node(op[1])
        elif kind == "pg_owner":
            _, owner, records = op
            with self._pg_lock:
                self._pg_table[owner] = list(records)
                self._pg_version += 1

    def _count_persist_error(self, where: str) -> None:
        """Satellite to the old bare ``except OSError: pass``: every
        persistence failure is counted, flight-recorded, and opens a
        back-off window (same degrade-don't-die discipline as the
        spill tier's disk-full path) so a full disk costs durability,
        not the control plane."""
        from ray_tpu._private import flight_recorder

        with self._persist_lock:
            self._persist_stats["persist_errors"] += 1
            self._persist_backoff_until = (
                time.monotonic() + _PERSIST_BACKOFF_S)
        flight_recorder.record("gcs.persist_error", where)

    def persist_stats(self) -> dict:
        """Counters + live epoch, served over RPC (drivers fold them
        into /metrics as the ray_tpu_gcs_* families)."""
        with self._persist_lock:
            out = dict(self._persist_stats)
        out["epoch"] = self.epoch
        out["armed"] = self._persist_armed
        out["fencing"] = self._fencing
        return out

    def _cluster_resources(self) -> dict:
        total: dict[str, float] = {}
        for r in self.gcs.list_nodes():
            if not r.alive:
                continue
            for k, v in r.resources.items():
                total[k] = total.get(k, 0.0) + v
        return total

    # -- lifecycle ----------------------------------------------------
    def start(self) -> None:
        self._server.start()
        self._monitor.start()

    def _monitor_loop(self) -> None:
        """Mark nodes dead when heartbeats go stale (reference:
        gcs_health_check_manager.h:39); snapshot persistent state when
        dirty."""
        while not self._shutdown.wait(1.0):
            now = time.monotonic()
            alive_ids = set()
            for record in self.gcs.list_nodes():
                if record.alive and (now - record.last_heartbeat
                                     > self.heartbeat_timeout_s):
                    self.gcs.mark_node_dead(record.node_id)
                elif record.alive:
                    alive_ids.add(record.node_id.hex())
            # Dead/churned nodes must not leak change-detection state
            # (or stale per-node stats series in /metrics).
            with self._avail_lock:
                for hex_id in list(self._last_published_avail):
                    if hex_id not in alive_ids:
                        self._last_published_avail.pop(hex_id, None)
            for hex_id in list(self.gcs.node_stats()):
                if hex_id not in alive_ids:
                    self.gcs.drop_node_stats(hex_id)
            self._prune_object_locations()
            self.pubsub.prune()
            self._history_tick()
            if self._persist_path:
                self._persist_tick()

    # -- persistence --------------------------------------------------
    def _kv_put(self, key: bytes, value: bytes,
                namespace: str = "default",
                overwrite: bool = True) -> bool:
        ok = self.gcs.kv.put(key, value, namespace, overwrite)
        if ok and self._wal is not None:
            self._wal_append(("kv_put", namespace, key, value))
        return ok

    def _kv_del(self, key: bytes, namespace: str = "default") -> bool:
        existed = self.gcs.kv.delete(key, namespace)
        if existed and self._wal is not None:
            self._wal_append(("kv_del", namespace, key))
        return existed

    def _dirty_version(self):
        """Per-table change counters (satellite to the old
        kv.version + job-status tuple, which never saw actor/node/PG
        mutations). JobManager mutates some record fields in place, so
        the job-status tuple stays in the mix."""
        with self._pg_lock:
            pg_version = self._pg_version
        return (self.gcs.kv.version, dict(self.gcs.table_versions),
                self.object_directory.version, pg_version,
                tuple(sorted((r.submission_id, r.status, r.message)
                             for r in self.gcs.list_jobs())))

    def _persist_tick(self, force: bool = False) -> None:
        """Monitor-tick persistence. Armed: mutations are already
        durable in the WAL, so the FULL snapshot lands only every
        ``gcs_snapshot_interval_s``, when the WAL outgrows
        ``gcs_wal_max_mb``, or at shutdown — then the WAL rotates.
        Disarmed: the legacy dirty-check {kv, jobs} snapshot, every
        tick, byte-identical to the pre-WAL head."""
        if not self._persist_armed:
            self._save_snapshot()
            return
        from ray_tpu._private.config import GLOBAL_CONFIG

        now = time.monotonic()
        with self._persist_lock:
            if now < self._persist_backoff_until:
                return
        if self._shards is not None:
            # Per-shard snapshots+rotation (each domain decides its own
            # dirtiness; a wedged one is skipped — it heals and drains
            # inside the stall check, bounding post-stall staleness to
            # one monitor tick).
            for shard in self._shards:
                shard.maybe_snapshot(
                    float(GLOBAL_CONFIG.gcs_snapshot_interval_s),
                    float(GLOBAL_CONFIG.gcs_wal_max_mb),
                    bool(GLOBAL_CONFIG.gcs_wal_fsync), force=force)
        wal_over = (self._wal is not None and self._wal.size()
                    > float(GLOBAL_CONFIG.gcs_wal_max_mb) * 1024 * 1024)
        interval = float(GLOBAL_CONFIG.gcs_snapshot_interval_s)
        if not force and not wal_over \
                and now - self._last_snapshot_at < interval:
            return
        version = self._dirty_version()
        if version == self._persisted_version and not wal_over:
            self._last_snapshot_at = now
            return
        self._save_snapshot_full()

    def _save_snapshot_full(self) -> None:
        import pickle

        from ray_tpu._private import gcs_persistence as gp
        from ray_tpu._private.config import GLOBAL_CONFIG

        version = self._dirty_version()
        # The seq captured BEFORE the table dump: a mutation landing
        # between capture and dump is both in the snapshot and (seq >
        # wal_seq) replayed — harmless, ops are idempotent upserts.
        with self._persist_lock:
            wal_seq = self._wal_seq
        with self._pg_lock:
            pgs = {o: list(r) for o, r in self._pg_table.items()}
        state = {
            "format": 2, "wal_seq": wal_seq, "epoch": self.epoch,
            "kv": self.gcs.kv.snapshot(),
            **self.gcs.control_snapshot(),
            "directory": (self.object_directory.snapshot_state()
                          if self._shards is None else {}),
            "placement_groups": pgs,
        }
        if self._shards is not None:
            # The directory lives in the per-shard segments; recording
            # the ring size here is what lets restore refuse a changed
            # gcs_shards typed instead of misrouting.
            state["gcs_shards"] = self._shard_count
        try:
            gp.write_snapshot(
                self._persist_path,
                pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL),
                fsync=bool(GLOBAL_CONFIG.gcs_wal_fsync))
            if self._wal is not None:
                self._wal.rotate()
        except OSError:
            self._count_persist_error("snapshot")
            return
        self._persisted_version = version
        self._last_snapshot_at = time.monotonic()
        with self._persist_lock:
            self._persist_stats["snapshots_written"] += 1

    def _restore_full(self) -> None:
        """Crash recovery: newest good snapshot (current, else .prev —
        reject-don't-crash on a torn one), then WAL replay with
        seq-gating and torn-tail truncation. Counted + flight-recorded
        so head recovery is observable, not hoped-for."""
        import pickle

        from ray_tpu._private import flight_recorder
        from ray_tpu._private import gcs_persistence as gp

        t0 = time.perf_counter()
        state = None
        for path in (self._persist_path, self._persist_path + ".prev"):
            try:
                state = pickle.loads(gp.read_snapshot(path))
                break
            except gp.TornSnapshotError:
                with self._persist_lock:
                    self._persist_stats["torn_snapshots"] += 1
                flight_recorder.record("gcs.torn_snapshot", path)
            except gp.LegacySnapshotError:
                # Pre-WAL head's raw-pickle {kv, jobs} file: load it
                # through the legacy path, then persist forward in the
                # framed format.
                self._restore_snapshot()
                return
            except FileNotFoundError:
                continue  # no snapshot at this path yet: first boot
            except (OSError, EOFError, pickle.UnpicklingError):
                # Unreadable (not merely absent) snapshot: count and
                # flight-record it — restore falls back to .prev +
                # WAL, but silently eating a corrupt current snapshot
                # is how durability bugs hide (the PR 12 lesson).
                with self._persist_lock:
                    self._persist_stats["persist_errors"] += 1
                flight_recorder.record("gcs.persist_error",
                                       "restore", path)
                continue
        base_seq = 0
        if state is not None:
            recorded = int(state.get("gcs_shards", 1))
            if recorded != self._shard_count:
                # The stable router's ring changed: loading this layout
                # would misroute restored entries — refuse typed.
                raise gp.ReshardError(recorded, self._shard_count)
            base_seq = int(state.get("wal_seq", 0))
            self.gcs.kv.restore(state.get("kv", {}))
            self.gcs.restore_control(state)
            self.object_directory.restore_state(
                state.get("directory") or {})
            with self._pg_lock:
                self._pg_table.update(
                    state.get("placement_groups") or {})
        replayed = skipped = torn = 0
        last_seq = base_seq
        for wal_path in (self._persist_path + ".wal.prev",
                         self._persist_path + ".wal"):
            stats = gp.replay_wal(wal_path, base_seq, self._apply_wal_op)
            replayed += stats["replayed"]
            skipped += stats["skipped"]
            torn += stats["truncated"]
            last_seq = max(last_seq, stats["last_seq"])
        self._wal_seq = last_seq
        # Restored RUNNING jobs: their entrypoint processes died with
        # the old head (legacy-restore semantics, kept).
        for record in self.gcs.list_jobs():
            if record.status == "RUNNING":
                self.gcs.finish_job(record.job_id, status="FAILED")
        restore_ms = (time.perf_counter() - t0) * 1000.0
        with self._persist_lock:
            self._persist_stats["wal_records_replayed"] += replayed
            self._persist_stats["wal_replay_skipped"] += skipped
            self._persist_stats["torn_wal_tails"] += torn
            self._persist_stats["snapshot_restore_ms"] = round(
                restore_ms, 3)
        if state is not None or replayed:
            flight_recorder.record(
                "gcs.restore", replayed, round(restore_ms, 1))

    def _save_snapshot(self) -> None:
        """Legacy (gcs_persistence=0) snapshot: {kv, jobs} raw pickle,
        byte-identical to the pre-WAL head — except the old bare
        ``except OSError: pass`` now counts, flight-records and backs
        off (degrade-don't-die, same discipline as spill disk-full)."""
        import pickle

        if time.monotonic() < self._persist_backoff_until:
            return
        version = (self.gcs.kv.version,
                   tuple(sorted((r.submission_id, r.status)
                                for r in self.gcs.list_jobs())))
        if version == self._persisted_version:
            return
        state = {
            "kv": self.gcs.kv.snapshot(),
            "jobs": [{
                "job_id": r.job_id.binary(), "status": r.status,
                "entrypoint": r.entrypoint, "message": r.message,
                "submission_id": r.submission_id,
                "start_time": r.start_time, "end_time": r.end_time,
            } for r in self.gcs.list_jobs()],
        }
        tmp = self._persist_path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self._persist_path)  # atomic swap
            self._persisted_version = version
        except OSError:
            self._count_persist_error("snapshot_legacy")

    def _restore_snapshot(self) -> None:
        import pickle

        try:
            with open(self._persist_path, "rb") as f:
                state = pickle.load(f)
        except (OSError, EOFError, pickle.UnpicklingError):
            return
        self.gcs.kv.restore(state.get("kv", {}))
        for j in state.get("jobs", []):
            record = JobRecord(
                job_id=JobID(j["job_id"]), entrypoint=j["entrypoint"],
                message=j["message"], submission_id=j["submission_id"],
                start_time=j["start_time"], end_time=j["end_time"],
                # Entrypoint processes did not survive the head restart.
                status="FAILED" if j["status"] == "RUNNING"
                else j["status"])
            self.gcs.register_job(record)

    def stop(self) -> None:
        self._shutdown.set()
        self.jobs.shutdown()
        if self._persist_path:
            # Final snapshot: mutations from the last monitor tick must
            # survive a clean shutdown.
            self._persist_tick(force=True)
        if self._wal is not None:
            self._wal.close()
        if self._shards is not None:
            for shard in self._shards:
                shard.close()
        self._server.stop()
