"""GCS server — the head node's control plane, served over RPC.

Reference: src/ray/gcs/gcs_server/gcs_server.h (GcsServer hosts the
node/actor/job/KV services over gRPC; python/ray/_private/gcs_utils.py
is the client side). Here one RpcServer exposes a
GlobalControlService's tables plus the job manager to every node,
driver, and CLI in the cluster.

Heartbeat failure detection matches the reference's
gcs_health_check_manager.h: nodes that miss heartbeats past the
threshold are marked dead and published on the node channel.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from typing import Any

from ray_tpu._private.gcs import (
    GlobalControlService,
    JobRecord,
    NodeRecord,
)
from ray_tpu._private.ids import JobID, NodeID
from ray_tpu._private.rpc import RpcServer


class JobManager:
    """Head-side job submission (reference:
    dashboard/modules/job/job_manager.py — entrypoint subprocesses with
    captured logs and terminal-state tracking)."""

    def __init__(self, gcs: GlobalControlService, log_dir: str):
        self.gcs = gcs
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._procs: dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def submit(self, entrypoint: str, *, submission_id: str | None = None,
               env: dict | None = None, cwd: str | None = None) -> str:
        job_id = JobID()
        sub_id = submission_id or f"raysubmit_{job_id.hex()[:12]}"
        # Idempotent on submission_id: a client retrying a dropped RPC
        # (rpc.py reconnect) must not launch the entrypoint twice. The
        # check-and-register is atomic under the lock (two server
        # threads can carry the same retried request concurrently).
        with self._lock:
            if submission_id is not None \
                    and self._record(sub_id) is not None:
                return sub_id
            self.gcs.register_job(JobRecord(
                job_id=job_id, status="RUNNING", entrypoint=entrypoint,
                submission_id=sub_id))
        log_path = os.path.join(self.log_dir, f"{sub_id}.log")
        full_env = dict(os.environ)
        # A submitted driver connects back to THIS head by default.
        full_env["RAY_TPU_JOB_SUBMISSION_ID"] = sub_id
        # Entrypoints must resolve the same ray_tpu installation as the
        # head (reference: job drivers inherit the cluster's ray).
        import ray_tpu

        pkg_file = getattr(ray_tpu, "__file__", None)
        if pkg_file:
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(pkg_file)))
            prior = full_env.get("PYTHONPATH", "")
            if pkg_root not in prior.split(os.pathsep):
                full_env["PYTHONPATH"] = (
                    pkg_root + (os.pathsep + prior if prior else ""))
        full_env.update(env or {})
        try:
            log_file = open(log_path, "wb")
            proc = subprocess.Popen(
                entrypoint, shell=True, stdout=log_file,
                stderr=subprocess.STDOUT, cwd=cwd, env=full_env,
                start_new_session=True)
        except OSError as exc:
            self.gcs.finish_job(job_id, status="FAILED")
            record = self._record(sub_id)
            if record is not None:
                record.message = str(exc)
            return sub_id
        with self._lock:
            self._procs[sub_id] = proc
        threading.Thread(target=self._wait, args=(sub_id, job_id, proc),
                         daemon=True, name=f"job-wait-{sub_id}").start()
        return sub_id

    def _wait(self, sub_id: str, job_id: JobID,
              proc: subprocess.Popen) -> None:
        rc = proc.wait()
        record = self._record(sub_id)
        if record is not None and record.status == "STOPPED":
            # User-stopped (SIGTERM): keep STOPPED, don't report FAILED.
            self.gcs.finish_job(job_id, status="STOPPED")
        else:
            self.gcs.finish_job(
                job_id, status="SUCCEEDED" if rc == 0 else "FAILED")
            if record is not None:
                record.message = f"exit code {rc}"
        with self._lock:
            self._procs.pop(sub_id, None)

    def _record(self, sub_id: str) -> JobRecord | None:
        for record in self.gcs.list_jobs():
            if record.submission_id == sub_id:
                return record
        return None

    def status(self, sub_id: str) -> dict | None:
        record = self._record(sub_id)
        if record is None:
            return None
        return {
            "submission_id": record.submission_id,
            "status": record.status,
            "entrypoint": record.entrypoint,
            "message": record.message,
            "start_time": record.start_time,
            "end_time": record.end_time,
        }

    def logs(self, sub_id: str, tail_bytes: int = 1 << 20) -> bytes:
        path = os.path.join(self.log_dir, f"{sub_id}.log")
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read()
        except FileNotFoundError:
            return b""

    def stop(self, sub_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(sub_id)
        if proc is None:
            return False
        import signal

        # STOPPED first: the exit-watcher reads this the moment the
        # SIGTERM'd process exits, and must not report FAILED.
        record = self._record(sub_id)
        if record is not None:
            record.status = "STOPPED"
            record.end_time = time.time()
        try:  # the whole session: entrypoints may spawn children
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            proc.terminate()
        return True

    def list(self) -> list[dict]:
        return [self.status(r.submission_id)
                for r in self.gcs.list_jobs() if r.submission_id]

    def shutdown(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
        for proc in procs:
            try:
                proc.terminate()
            except OSError:
                pass


class GcsServer:
    """RPC facade over GlobalControlService + JobManager + cluster KV."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 log_dir: str = "/tmp/ray_tpu/session",
                 heartbeat_timeout_s: float | None = None,
                 persist_path: str | None = None):
        from ray_tpu._private.config import GLOBAL_CONFIG

        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = float(
                GLOBAL_CONFIG.gcs_heartbeat_timeout_s)
        # Native (C++) storage engine for the head's tables (reference:
        # the GCS storage layer is C++, in_memory_store_client.h:31);
        # gated by the same config convention as the daemon blob store.
        kv = None
        if bool(GLOBAL_CONFIG.gcs_kv_native):
            from ray_tpu._private.gcs_kv_native import make_kv_store

            kv = make_kv_store()
        self.gcs = GlobalControlService(kv=kv)
        self.jobs = JobManager(self.gcs, os.path.join(log_dir, "jobs"))
        self.heartbeat_timeout_s = heartbeat_timeout_s
        # Fault tolerance: KV (incl. the cluster actor directory) + job
        # table snapshot to disk, restored on restart (reference:
        # store_client/redis_store_client.h:33 — redis-backed GCS FT;
        # here a file-backed snapshot, same recovery semantics).
        self._persist_path = persist_path
        self._persisted_version = -1
        if persist_path:
            self._restore_snapshot()
        self._server = RpcServer(host, port)
        self._shutdown = threading.Event()
        # Cluster object-location directory (multi-holder; pruned when
        # an owner stops refreshing its lease — its driver exited).
        from ray_tpu._private.gcs import ObjectDirectory

        self.object_directory = ObjectDirectory()
        # Cross-process channel hub; the head's own membership events
        # bridge onto the "nodes" channel so any cluster process can
        # react by push instead of polling list_nodes.
        from ray_tpu._private.gcs_pubsub import ChannelHub

        self.pubsub = ChannelHub()
        self.gcs.pubsub.subscribe("nodes", self._on_node_event)
        # Last availability published per node (change detection for
        # the "node_resources" syncer channel).
        self._last_published_avail: dict[str, dict] = {}
        self._avail_lock = threading.Lock()
        # Daemon trace spans shipped on heartbeats, staged until a
        # driver drains them into its merged timeline. Bounded: a
        # cluster tracing with no driver exporting must not grow this
        # without limit.
        self._trace_spans: list[dict] = []
        self._trace_lock = threading.Lock()
        self._register_methods()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="gcs-monitor")

    @property
    def address(self) -> str:
        return self._server.address

    def _register_methods(self) -> None:
        s = self._server
        s.register("ping", lambda: "pong")
        # KV (reference: gcs InternalKV service).
        s.register("kv_put", self.gcs.kv.put)
        s.register("kv_get", self.gcs.kv.get)
        s.register("kv_del", self.gcs.kv.delete)
        s.register("kv_exists", self.gcs.kv.exists)
        s.register("kv_keys", self.gcs.kv.keys)
        # Nodes.
        s.register("register_node", self._register_node)
        s.register("heartbeat", self._heartbeat)
        s.register("list_nodes", self._list_nodes)
        s.register("drain_node", self._drain_node)
        # Jobs.
        s.register("submit_job", self.jobs.submit)
        s.register("job_status", self.jobs.status)
        s.register("job_logs", self.jobs.logs)
        s.register("stop_job", self.jobs.stop)
        s.register("list_jobs", self.jobs.list)
        # Cluster-wide info.
        s.register("cluster_resources", self._cluster_resources)
        # Observability: per-node executor stats (heartbeat-pushed;
        # drivers fold them into /metrics as labeled series) and the
        # heartbeat-shipped daemon trace spans.
        s.register("node_stats", self.gcs.node_stats)
        s.register("drain_trace_spans", self._drain_trace_spans)
        # Object-location table (reference:
        # ownership_based_object_directory.h — owner -> holding nodes;
        # here owners batch-publish their primary-copy locations).
        s.register("object_locations_update",
                   self._object_locations_update)
        s.register("list_object_locations", self._list_object_locations)
        # Cluster-wide pub/sub channels (reference: the GCS pubsub
        # handler over src/ray/pubsub/publisher.h:307). Polls block, so
        # they dispatch concurrently like task execution does.
        s.register("pubsub_subscribe", self.pubsub.subscribe)
        s.register("pubsub_unsubscribe", self.pubsub.unsubscribe)
        s.register("pubsub_publish", self.pubsub.publish)
        s.register("pubsub_poll", self.pubsub.poll, concurrent=True)

    # -- node service -------------------------------------------------
    def _on_node_event(self, event) -> None:
        """Bridge membership events onto the cluster channel hub; a
        DEAD verdict additionally prunes the dead node from every
        object-directory holder set and publishes the objects whose
        last holder died, so owners stop being handed dead holders and
        can fire lineage reconstruction by push (reference:
        GcsNodeManager node-dead broadcast + the directory dropping the
        node's locations)."""
        kind, node_id = event
        if kind == "DEAD":
            orphaned = self.object_directory.prune_node(node_id.hex())
            if orphaned:
                self.pubsub.publish("object_loss", orphaned)
        self.pubsub.publish("nodes", (kind, node_id.hex()))

    def _register_node(self, address: str, resources: dict,
                       labels: dict | None = None,
                       executor_address: str = "",
                       prior_id: bytes | None = None,
                       host_id: str = "") -> bytes:
        """``prior_id``: a daemon re-registering after its heartbeat was
        rejected asks to KEEP its id. Granted when this head has never
        seen the id (head restart amnesia — reference: raylets keep
        their NodeID across a GCS restart) or when the record matches
        (retry of a lost reply). Refused when the id is known DEAD: the
        death verdict stands, recovery may already be re-executing its
        lineage — the daemon comes back as a fresh node."""
        node_id = None
        if prior_id is not None:
            candidate = NodeID(prior_id)
            existing = self.gcs.get_node(candidate)
            if existing is None or (existing.alive
                                    and existing.address == address):
                node_id = candidate
        if node_id is None:
            node_id = NodeID()
        self.gcs.register_node(NodeRecord(
            node_id=node_id, address=address, resources=dict(resources),
            labels=dict(labels or {}),
            executor_address=executor_address, host_id=host_id))
        return node_id.binary()

    def _heartbeat(self, node_id_bytes: bytes,
                   available: dict | None = None,
                   stats: dict | None = None,
                   trace: dict | None = None) -> bool:
        # False tells the agent it is unknown/dead and must re-register.
        accepted = self.gcs.heartbeat(NodeID(node_id_bytes), available)
        if accepted and stats is not None:
            # Spill-event piggyback: the daemon's spill tier reports
            # (owner, object hex, "spilled"|"restored") transitions so
            # the object directory stays spill-aware (popped — events
            # are deltas, not stats to aggregate).
            events = stats.pop("spill_events", None)
            if events:
                node_hex = node_id_bytes.hex()
                for owner, obj_hex, kind in events:
                    if kind == "spilled":
                        self.object_directory.mark_spilled(
                            owner, obj_hex, node_hex)
                    else:
                        self.object_directory.clear_spilled(
                            owner, obj_hex)
            # Executor-stats piggyback: the GCS-side aggregation table
            # drivers scrape into per-node /metrics series.
            self.gcs.record_node_stats(node_id_bytes.hex(), stats)
        if accepted and trace:
            # Daemon spans piggybacked on the heartbeat. The offset is
            # a one-way estimate (recv wall clock minus the daemon's
            # send stamp) — coarser than the half-RTT reply path, but
            # these spans had no driver reply to anchor on.
            spans = trace.get("spans") or []
            anchor = trace.get("now")
            offset = (time.time() - float(anchor)) if anchor else 0.0
            with self._trace_lock:
                room = 65536 - len(self._trace_spans)
                if room > 0:
                    self._trace_spans.append(
                        {"spans": spans[:room], "offset": offset,
                         "node": node_id_bytes.hex()})
        if accepted and available is not None:
            # Syncer push: availability CHANGES fan out on the
            # "node_resources" channel so drivers' schedulers track
            # other tenants' load without polling (reference: the
            # ray_syncer resource-view stream, ray_syncer.h:88).
            # Steady-state heartbeats with unchanged availability
            # publish nothing.
            hex_id = node_id_bytes.hex()
            with self._avail_lock:
                last = self._last_published_avail.get(hex_id)
                changed = last != available
                if changed:
                    self._last_published_avail[hex_id] = dict(available)
            if changed:
                self.pubsub.publish(
                    "node_resources", (hex_id, dict(available)))
        return accepted

    def _list_nodes(self) -> list[dict]:
        return [{
            "node_id": r.node_id.hex(),
            "address": r.address,
            "resources": dict(r.resources),
            "available": dict(r.available),
            "labels": dict(r.labels),
            "executor_address": r.executor_address,
            "host_id": r.host_id,
            "alive": r.alive,
        } for r in self.gcs.list_nodes()]

    def _drain_node(self, node_id_bytes: bytes) -> bool:
        self.gcs.mark_node_dead(NodeID(node_id_bytes))
        self.gcs.drop_node_stats(node_id_bytes.hex())
        return True

    def _drain_trace_spans(self) -> list[dict]:
        """Hand the staged heartbeat-shipped span batches to the
        draining driver (one-shot: drained batches are gone)."""
        with self._trace_lock:
            out, self._trace_spans = self._trace_spans, []
            return out

    def _object_locations_update(self, owner: str, adds: list,
                                 removes: list) -> int:
        """Batched owner-published location deltas; an empty update is a
        keepalive that refreshes the owner's lease on its entries."""
        return self.object_directory.update(owner, adds, removes)

    def _list_object_locations(self, owner: str | None = None,
                               include_spilled: bool = False):
        """Holder table, optionally paired with the spilled-location
        view (``include_spilled``): consumers like the locality scorer
        discount holders whose only copy is on disk."""
        locations = self.object_directory.locations(owner)
        if not include_spilled:
            return locations
        return (locations, self.object_directory.spilled(owner))

    def _prune_object_locations(self, ttl_s: float = 60.0) -> None:
        self.object_directory.prune(ttl_s)

    def _cluster_resources(self) -> dict:
        total: dict[str, float] = {}
        for r in self.gcs.list_nodes():
            if not r.alive:
                continue
            for k, v in r.resources.items():
                total[k] = total.get(k, 0.0) + v
        return total

    # -- lifecycle ----------------------------------------------------
    def start(self) -> None:
        self._server.start()
        self._monitor.start()

    def _monitor_loop(self) -> None:
        """Mark nodes dead when heartbeats go stale (reference:
        gcs_health_check_manager.h:39); snapshot persistent state when
        dirty."""
        while not self._shutdown.wait(1.0):
            now = time.monotonic()
            alive_ids = set()
            for record in self.gcs.list_nodes():
                if record.alive and (now - record.last_heartbeat
                                     > self.heartbeat_timeout_s):
                    self.gcs.mark_node_dead(record.node_id)
                elif record.alive:
                    alive_ids.add(record.node_id.hex())
            # Dead/churned nodes must not leak change-detection state
            # (or stale per-node stats series in /metrics).
            with self._avail_lock:
                for hex_id in list(self._last_published_avail):
                    if hex_id not in alive_ids:
                        self._last_published_avail.pop(hex_id, None)
            for hex_id in list(self.gcs.node_stats()):
                if hex_id not in alive_ids:
                    self.gcs.drop_node_stats(hex_id)
            self._prune_object_locations()
            self.pubsub.prune()
            if self._persist_path:
                self._save_snapshot()

    # -- persistence --------------------------------------------------
    def _save_snapshot(self) -> None:
        import pickle

        version = (self.gcs.kv.version,
                   tuple(sorted((r.submission_id, r.status)
                                for r in self.gcs.list_jobs())))
        if version == self._persisted_version:
            return
        state = {
            "kv": self.gcs.kv.snapshot(),
            "jobs": [{
                "job_id": r.job_id.binary(), "status": r.status,
                "entrypoint": r.entrypoint, "message": r.message,
                "submission_id": r.submission_id,
                "start_time": r.start_time, "end_time": r.end_time,
            } for r in self.gcs.list_jobs()],
        }
        tmp = self._persist_path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self._persist_path)  # atomic swap
            self._persisted_version = version
        except OSError:
            pass  # disk hiccup: retry next tick

    def _restore_snapshot(self) -> None:
        import pickle

        try:
            with open(self._persist_path, "rb") as f:
                state = pickle.load(f)
        except (OSError, EOFError, pickle.UnpicklingError):
            return
        self.gcs.kv.restore(state.get("kv", {}))
        for j in state.get("jobs", []):
            record = JobRecord(
                job_id=JobID(j["job_id"]), entrypoint=j["entrypoint"],
                message=j["message"], submission_id=j["submission_id"],
                start_time=j["start_time"], end_time=j["end_time"],
                # Entrypoint processes did not survive the head restart.
                status="FAILED" if j["status"] == "RUNNING"
                else j["status"])
            self.gcs.register_job(record)

    def stop(self) -> None:
        self._shutdown.set()
        self.jobs.shutdown()
        if self._persist_path:
            # Final snapshot: mutations from the last monitor tick must
            # survive a clean shutdown.
            self._save_snapshot()
        self._server.stop()
