"""Per-call request context: the in-flight call's end-to-end deadline.

The PR-7 deadline machinery stamps an ABSOLUTE deadline onto every
task/actor call and checks it at each pipeline stage — but until now
the budget was invisible to the USER CODE the call finally runs. A
serve replica hosting a long-lived engine (the LLM engine's internal
waiting queue and decode loop) needs the remaining budget so ITS
stages can refuse dead work too, instead of decoding tokens nobody is
waiting for.

The actor runtimes set the contextvar around each method invocation;
``ray_tpu.runtime_context.get_runtime_context().get_task_deadline()``
reads it from inside the method (None = no deadline armed).
Contextvars propagate into coroutines and stay isolated per thread, so
concurrent actor calls never see each other's budgets.
"""

from __future__ import annotations

import contextvars

_DEADLINE: "contextvars.ContextVar[float | None]" = contextvars.ContextVar(
    "ray_tpu_call_deadline", default=None)


def set_deadline(deadline: "float | None"):
    """Install the current call's absolute deadline (time.time());
    returns the token for :func:`reset_deadline`."""
    return _DEADLINE.set(deadline)


def reset_deadline(token) -> None:
    _DEADLINE.reset(token)


def current_deadline() -> "float | None":
    """The in-flight call's absolute deadline, or None."""
    return _DEADLINE.get()
