"""Cluster-wide pub/sub channels served by the head GCS.

Reference: src/ray/pubsub/publisher.h:307 (Publisher buffers messages
per subscriber and drains them on long-poll requests; subscriber.h:70
is the polling client) and python/ray/_private/gcs_pubsub.py. Channels
are free-form strings; the head publishes its own node-membership
events on ``nodes``, and any process in the cluster can publish or
subscribe through the head's RPC surface:

    pubsub_subscribe(sub_id, channels)
    pubsub_poll(sub_id, timeout) -> [(channel, message), ...]
    pubsub_publish(channel, message) -> receiver count
    pubsub_unsubscribe(sub_id)

Subscribers that stop polling past a TTL are pruned (their buffers
would otherwise grow unbounded — same reason the reference caps
per-subscriber buffers).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any


class ChannelHub:
    """Server-side channel fan-out with per-subscriber buffers."""

    def __init__(self, max_buffer: int = 1000,
                 subscriber_ttl_s: float = 60.0):
        self._cond = threading.Condition(threading.Lock())
        self._max_buffer = max_buffer
        self._ttl = subscriber_ttl_s
        # sub_id -> {"channels": set, "queue": deque, "seen": float,
        #            "dropped": int}
        self._subs: dict[str, dict] = {}

    def subscribe(self, sub_id: str, channels: list[str]) -> None:
        with self._cond:
            self._prune_locked(time.monotonic())
            sub = self._subs.setdefault(sub_id, {
                "channels": set(), "queue": collections.deque(),
                "seen": time.monotonic(), "dropped": 0, "epoch": 0})
            sub["channels"].update(channels)
            sub["seen"] = time.monotonic()

    def _prune_locked(self, now: float) -> None:
        for sub_id in list(self._subs):
            if now - self._subs[sub_id]["seen"] > self._ttl:
                del self._subs[sub_id]

    def prune(self) -> None:
        """Periodic sweep (the head's monitor loop): dead subscribers'
        buffers must not outlive the TTL just because their channels
        went quiet (publish-time pruning alone never fires then)."""
        with self._cond:
            self._prune_locked(time.monotonic())

    def unsubscribe(self, sub_id: str) -> bool:
        with self._cond:
            return self._subs.pop(sub_id, None) is not None

    def publish(self, channel: str, message: Any) -> int:
        """Fan ``message`` out to the channel's subscribers."""
        delivered = 0
        with self._cond:
            now = time.monotonic()
            self._prune_locked(now)
            for sub_id in list(self._subs):
                sub = self._subs[sub_id]
                if channel not in sub["channels"]:
                    continue
                queue = sub["queue"]
                if len(queue) >= self._max_buffer:
                    queue.popleft()  # oldest-first drop, counted
                    sub["dropped"] += 1
                queue.append((channel, message))
                delivered += 1
            if delivered:
                self._cond.notify_all()
        return delivered

    def poll(self, sub_id: str, timeout_s: float = 10.0) -> list | None:
        """Drain the subscriber's buffer, blocking up to ``timeout_s``
        for the first message (the long-poll shape: the server holds
        the request, the client loops). ``None`` means the subscriber
        is unknown/pruned — re-subscribe."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            sub = self._subs.get(sub_id)
            if sub is None:
                return None
            # Single-drainer epoch: a NEWER poll for the same sub_id
            # supersedes this one (the client re-polled after a dropped
            # connection); the superseded waiter must return WITHOUT
            # draining, or its reply dies on the dead socket and the
            # drained events are lost.
            sub["epoch"] = sub.get("epoch", 0) + 1
            my_epoch = sub["epoch"]
            while True:
                sub = self._subs.get(sub_id)
                if sub is None:
                    return None
                if sub.get("epoch", 0) != my_epoch:
                    return []  # superseded by a fresh poll
                sub["seen"] = time.monotonic()
                if sub["queue"]:
                    out = list(sub["queue"])
                    sub["queue"].clear()
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(min(remaining, 1.0))

    def num_subscribers(self) -> int:
        with self._cond:
            return len(self._subs)


class GcsSubscriber:
    """Client half (reference: subscriber.h:70 / gcs_pubsub.py
    GcsSubscriber): subscribe once, then loop poll(); re-subscribes
    transparently if the head pruned or restarted."""

    # Server polls must resolve well inside the RPC socket timeout, or
    # every long poll would die as a zombie thread holding the buffer.
    _MAX_POLL_S = 25.0

    def __init__(self, address: str, channels: list[str]):
        from ray_tpu._private.rpc import RpcClient

        self._client = RpcClient(address, timeout_s=30.0)
        self._channels = list(channels)
        self.sub_id = os.urandom(8).hex()
        try:
            self._client.call("pubsub_subscribe", self.sub_id,
                              self._channels)
        except BaseException:
            self._client.close()  # never leak the connected socket
            raise

    def poll(self, timeout_s: float = 10.0) -> list:
        events = self._client.call("pubsub_poll", self.sub_id,
                                   min(timeout_s, self._MAX_POLL_S))
        if events is None:
            # Pruned (or head restarted): re-subscribe and retry once.
            self._client.call("pubsub_subscribe", self.sub_id,
                              self._channels)
            events = self._client.call("pubsub_poll", self.sub_id, 0.0)
        return events or []

    def close(self) -> None:
        # No goodbye RPC: with the head unreachable it would block a
        # whole socket timeout inside shutdown paths. The hub prunes
        # silent subscribers by TTL.
        self._client.close()


class GcsPublisher:
    """Client publish half (reference: gcs_pubsub.py GcsPublisher)."""

    def __init__(self, address: str):
        from ray_tpu._private.rpc import RpcClient

        self._client = RpcClient(address, timeout_s=10.0)

    def publish(self, channel: str, message: Any) -> int:
        return self._client.call("pubsub_publish", channel, message)

    def close(self) -> None:
        self._client.close()
