"""Deterministic fault injection for the distributed fast paths.

TPU-native analogue of the reference's chaos tooling
(python/ray/_private/test_utils.py NodeKillerActor and the
RAY_testing_* failure-injection config entries): named injection
points are threaded through the transport (rpc.py), the node agent
(node.py) and the same-host lease plane (same_host.py). Production
builds pay ONE branch per site — ``chaos.ACTIVE`` is a module global
that stays ``None`` unless ``RAY_TPU_CHAOS`` is set, so every site is
``if chaos.ACTIVE is not None and ...``.

Spec grammar (``RAY_TPU_CHAOS`` or ``configure()``)::

    seed=42,rpc.sever=0.1,rpc.drop_frame=0.05x3,heartbeat.skip=1.0

``site=rate`` fires with probability ``rate`` per hit from ONE seeded
RNG (same seed + same call order => same fire pattern, the property
the deterministic tier-1 chaos tests assert); ``site=ratexN`` caps the
site at N total fires (``1.0x1`` = "exactly the first hit").

Injection sites (the site string is the contract; counters surface in
``ChaosController.stats()``):

- ``rpc.sever``       client: fail the connection before a frame send
- ``rpc.drop_frame``  client: silently drop one request frame
- ``rpc.delay``       client: sleep 5-50 ms before a frame send
- ``rpc.kill_stream`` server: kill a streaming reply mid-parts
- ``net.partition``   client: SUSTAINED partition — one fire opens a
  seeded window (``RAY_TPU_PARTITION_S`` base seconds x a seeded
  0.5-1.5 jitter) during which EVERY send to that destination fails
  like a severed link, then the window heals and traffic resumes (vs
  ``rpc.sever``'s one-shot failure). ``RAY_TPU_PARTITION_TARGET``
  restricts the site to destinations containing the substring (e.g.
  the head's port) so tests sever exactly the node<->head or
  node<->node link they mean to
- ``gcs.torn_snapshot`` head persistence: truncate a GCS snapshot's
  payload mid-write under a full-length header — restore must detect
  the tear by CRC and fall back to the previous good snapshot + WAL
- ``gcs.torn_wal``      head persistence: write a WAL record's payload
  short under a full-length header (the SIGKILL-mid-append shape) —
  restart must truncate the torn tail and replay everything before it
- ``gcs.shard_die``     head shard plane (gcs_shards>1): crash-restart
  the shard owning the in-flight mutation mid-call — it replays only
  ITS WAL, mints its next epoch (the advertised epoch bumps, so the
  stale writer is fenced typed), and the other shards keep serving
- ``gcs.shard_stall``   head shard plane: wedge the owning shard for
  ``RAY_TPU_SHARD_STALL_S`` base seconds x a seeded 0.5-1.5 jitter —
  reads serve its stale view (age_s exposed), writes queue WAL-first
  and shed SystemOverloadedError typed past the bounded cap
- ``heartbeat.skip``  node agent: skip one heartbeat period
- ``daemon.die``      node agent: SIGKILL its own daemon process
- ``lease.expire``    same-host LeaseTable: expire a lease early
- ``overload.saturate`` daemon admission: shed the lease/batch as
  ``("overloaded", ...)`` — the driver fails deadline-armed tasks fast
  with SystemOverloadedError and spillback-requeues the rest (one draw
  per execute RPC / batch, node_executor._overload_reason)
- ``sched.straggle``   daemon exec: artificially delay this node's
  execution (``RAY_TPU_STRAGGLE_S`` seconds, default 2.0) BEFORE the
  user function runs — makes straggler-speculation triggers
  deterministic; the delay loop aborts early when the task's token is
  loser-cancelled, so first-seal-wins is provable with marker files
- ``spill.torn_write``  spill tier: truncate a spill file's payload
  mid-write (the header still promises the full length — the
  crash-mid-write shape); the next restore detects the tear by CRC
  and falls back to lineage reconstruction
- ``spill.disk_full``   spill tier: fail the spill write with
  SpillDiskFullError — the spiller backs off and admission degrades
  store pressure to the typed shed instead of crashing the daemon
- ``spill.restore_delay`` spill tier: sleep 50-500 ms before a
  restore read, racing restores against concurrent gets/frees
- ``llm.slow_step``     LLM engine: wedge one batched decode step for
  ``RAY_TPU_LLM_SLOW_S`` seconds (default 2.0) BEFORE the jitted step
  runs — proves a wedged decode trips the request deadline typed
  (TaskTimeoutError stage ``llm_decode`` sealed by the caller-side
  wait, exactly once) instead of hanging the stream; the sleep aborts
  early on engine shutdown so a wedged engine still tears down
"""

from __future__ import annotations

import os
import random
import threading


# Canonical injection-site registry — THE contract between code,
# spec strings, tests and docs. The analysis pass ``chaos-sites``
# (ray_tpu/_private/analysis/chaos_sites.py) mechanically enforces:
# every ``should("<site>")`` in the tree names a registered site, and
# every registered site is documented in this module's docstring and
# exercised somewhere under tests/. Add the site here FIRST.
SITES: "tuple[str, ...]" = (
    "rpc.sever",
    "rpc.drop_frame",
    "rpc.delay",
    "rpc.kill_stream",
    "net.partition",
    "gcs.torn_snapshot",
    "gcs.torn_wal",
    "gcs.shard_die",
    "gcs.shard_stall",
    "heartbeat.skip",
    "daemon.die",
    "lease.expire",
    "overload.saturate",
    "sched.straggle",
    "spill.torn_write",
    "spill.disk_full",
    "spill.restore_delay",
    "llm.slow_step",
)


class ChaosController:
    """Seeded, named injection points with per-site rates and caps."""

    def __init__(self, rates: "dict[str, tuple[float, int | None]]",
                 seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._rates = dict(rates)
        self._lock = threading.Lock()
        self.injected: dict[str, int] = {}
        # net.partition windows: destination address -> heal time
        # (monotonic). While a window is open EVERY send to that
        # destination fails; expiry heals the link in place.
        self._partitions: dict[str, float] = {}

    def should(self, site: str) -> bool:
        """One seeded draw for ``site``; True means the caller must
        inject the fault (and the fire was counted)."""
        entry = self._rates.get(site)
        if entry is None:
            return False
        rate, cap = entry
        if rate <= 0.0:
            return False
        with self._lock:
            if cap is not None and self.injected.get(site, 0) >= cap:
                return False
            fire = self._rng.random() < rate
            if fire:
                self.injected[site] = self.injected.get(site, 0) + 1
        if fire:
            # Every firing lands in the process's flight-recorder ring
            # (cheap tuple append): a post-mortem bundle shows which
            # injected faults this process absorbed before it died.
            from ray_tpu._private import flight_recorder

            flight_recorder.record("chaos", site)
            # Chaos firings become instant pins in merged timelines —
            # a soak trace shows WHERE each injected fault landed
            # relative to the pipeline stages around it. Lazy import +
            # one branch: tracing-off and chaos-off both pay nothing.
            from ray_tpu.util import tracing

            if tracing.TRACE_ON:
                tag = os.environ.get("RAY_TPU_NODE_TAG")
                if tag:
                    # Daemon process: queue for heartbeat piggyback so
                    # the pin lands in the DRIVER's merged timeline.
                    tracing.buffer_instant(f"chaos:{site}",
                                           f"node:{tag[:8]}",
                                           {"seed": self.seed})
                else:
                    tracing.instant(f"chaos:{site}", {"seed": self.seed})
        return fire

    def partitioned(self, dest: str) -> bool:
        """Is a partition window currently open toward ``dest``?
        Expired windows heal (and are forgotten) here."""
        import time

        with self._lock:
            heal = self._partitions.get(dest)
            if heal is None:
                return False
            if time.monotonic() >= heal:
                del self._partitions[dest]
                return False
            return True

    def maybe_partition(self, dest: str) -> bool:
        """One seeded ``net.partition`` draw for a send toward
        ``dest``; a fire opens the sustained window. Destinations not
        matching ``RAY_TPU_PARTITION_TARGET`` (when set) never draw —
        the RNG stream stays deterministic for the links under test."""
        import time

        target = os.environ.get("RAY_TPU_PARTITION_TARGET", "")
        if target and target not in dest:
            return False
        if not self.should("net.partition"):
            return False
        base = float(os.environ.get("RAY_TPU_PARTITION_S", "2.0"))
        duration = base * (0.5 + self.uniform())
        with self._lock:
            self._partitions[dest] = time.monotonic() + duration
        return True

    def uniform(self) -> float:
        """A seeded draw in [0, 1) for sites that need a magnitude
        (delay length) on top of the fire decision."""
        with self._lock:
            return self._rng.random()

    def stats(self) -> dict:
        with self._lock:
            return {"seed": self.seed, "injected": dict(self.injected)}


def _parse(spec: str) -> "tuple[dict, int]":
    rates: dict[str, tuple[float, int | None]] = {}
    seed = 0
    for item in spec.replace(";", ",").split(","):
        item = item.strip()
        if not item or "=" not in item:
            continue
        key, _, value = item.partition("=")
        key = key.strip()
        value = value.strip()
        if key == "seed":
            seed = int(value)
            continue
        cap: int | None = None
        if "x" in value:
            value, _, cap_s = value.partition("x")
            cap = int(cap_s)
        rates[key] = (float(value), cap)
    return rates, seed


# The ONE production branch: None unless chaos is configured.
ACTIVE: ChaosController | None = None


def configure(spec: "str | None") -> ChaosController | None:
    """Install (or clear, with a falsy spec) the process-wide
    controller. Tests call this directly; daemons inherit the
    ``RAY_TPU_CHAOS`` environment through ``daemon_child_env``."""
    global ACTIVE
    if not spec:
        ACTIVE = None
        return None
    rates, seed = _parse(spec)
    ACTIVE = ChaosController(rates, seed)
    return ACTIVE


def disable() -> None:
    configure(None)


def should(site: str) -> bool:
    """Convenience for non-hot paths; hot sites read ``ACTIVE``
    directly so the disabled cost is one attribute load."""
    controller = ACTIVE
    return controller is not None and controller.should(site)


# Env-driven install at import: spawned daemons enable chaos without
# any code path having to thread the flag (config.py declares the
# matching ``chaos`` knob for init(system_config=...) visibility).
_env_spec = os.environ.get("RAY_TPU_CHAOS", "")
if _env_spec:
    configure(_env_spec)
