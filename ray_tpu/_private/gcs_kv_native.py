"""ctypes binding for the native GCS KV storage engine (gcs_kv.cpp).

Reference: the GCS's storage layer is C++ (gcs_kv_manager.h,
store_client/in_memory_store_client.h:31); the Python control plane
keeps only this thin binding. Drop-in for gcs.KVStore — same methods,
same snapshot()/restore() dict shape (the head's crash persistence
pickles that dict) — selected by make_kv_store() with the pure-Python
store as the no-toolchain fallback.
"""

from __future__ import annotations

import ctypes
import struct
from typing import Iterable


def _bind(lib: ctypes.CDLL) -> None:
    lib.gcs_kv_create.restype = ctypes.c_void_p
    lib.gcs_kv_destroy.argtypes = [ctypes.c_void_p]
    lib.gcs_kv_version.restype = ctypes.c_uint64
    lib.gcs_kv_version.argtypes = [ctypes.c_void_p]
    lib.gcs_kv_put.restype = ctypes.c_int
    lib.gcs_kv_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int]
    lib.gcs_kv_get.restype = ctypes.c_long
    lib.gcs_kv_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t]
    lib.gcs_kv_del.restype = ctypes.c_int
    lib.gcs_kv_del.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_size_t]
    lib.gcs_kv_exists.restype = ctypes.c_int
    lib.gcs_kv_exists.argtypes = lib.gcs_kv_del.argtypes
    lib.gcs_kv_keys.restype = ctypes.c_long
    lib.gcs_kv_keys.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t]
    lib.gcs_kv_snapshot.restype = ctypes.c_long
    lib.gcs_kv_snapshot.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.gcs_kv_restore.restype = ctypes.c_long
    lib.gcs_kv_restore.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]


def _two_phase(call, start_cap: int = 4096) -> bytes | None:
    """Run a (buf, cap) -> needed-size native call, growing the buffer
    until the result fits. -1 means absent."""
    cap = start_cap
    while True:
        buf = ctypes.create_string_buffer(cap)
        need = call(buf, cap)
        if need < 0:
            return None
        if need <= cap:
            return buf.raw[:need]
        cap = int(need)


class NativeKVStore:
    """Same interface/semantics as gcs.KVStore, C++-backed."""

    def __init__(self, lib: ctypes.CDLL):
        _bind(lib)
        self._lib = lib
        self._h = lib.gcs_kv_create()

    def __del__(self):
        try:
            if self._h:
                self._lib.gcs_kv_destroy(self._h)
                self._h = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    @property
    def version(self) -> int:
        return int(self._lib.gcs_kv_version(self._h))

    def put(self, key: bytes, value: bytes, namespace: str = "default",
            overwrite: bool = True) -> bool:
        ret = self._lib.gcs_kv_put(
            self._h, namespace.encode(), key, len(key), value,
            len(value), 1 if overwrite else 0)
        if ret < 0:
            raise ValueError(
                "key/value exceeds the native KV's 4 GiB limit")
        return bool(ret)

    def get(self, key: bytes, namespace: str = "default") -> bytes | None:
        return _two_phase(lambda buf, cap: self._lib.gcs_kv_get(
            self._h, namespace.encode(), key, len(key), buf, cap))

    def delete(self, key: bytes, namespace: str = "default") -> bool:
        return bool(self._lib.gcs_kv_del(
            self._h, namespace.encode(), key, len(key)))

    def exists(self, key: bytes, namespace: str = "default") -> bool:
        return bool(self._lib.gcs_kv_exists(
            self._h, namespace.encode(), key, len(key)))

    def keys(self, prefix: bytes = b"",
             namespace: str = "default") -> list[bytes]:
        raw = _two_phase(lambda buf, cap: self._lib.gcs_kv_keys(
            self._h, namespace.encode(), prefix, len(prefix), buf, cap))
        if not raw:
            return []
        (count,) = struct.unpack_from("<I", raw, 0)
        off = 4
        out = []
        for _ in range(count):
            (n,) = struct.unpack_from("<I", raw, off)
            off += 4
            out.append(raw[off:off + n])
            off += n
        return out

    # -- persistence (same dict shape the Python store produces) ------
    def snapshot(self) -> dict:
        raw = _two_phase(lambda buf, cap: self._lib.gcs_kv_snapshot(
            self._h, buf, cap), start_cap=1 << 16)
        out: dict[str, dict[bytes, bytes]] = {}
        if not raw:
            return out
        (count,) = struct.unpack_from("<I", raw, 0)
        off = 4

        def blob():
            nonlocal off
            (n,) = struct.unpack_from("<I", raw, off)
            off += 4
            b = raw[off:off + n]
            off += n
            return b

        for _ in range(count):
            ns = blob().decode()
            key = blob()
            value = blob()
            out.setdefault(ns, {})[key] = value
        return out

    def restore(self, data: dict) -> None:
        image = bytearray()
        entries: list[tuple[bytes, bytes, bytes]] = []
        for ns, kv in data.items():
            for k, v in kv.items():
                entries.append((ns.encode(), k, v))
        image += struct.pack("<I", len(entries))
        for ns, k, v in entries:
            for blob in (ns, k, v):
                image += struct.pack("<I", len(blob)) + blob
        applied = self._lib.gcs_kv_restore(
            self._h, bytes(image), len(image))
        if applied < 0:
            raise ValueError("corrupt KV snapshot image")


def make_kv_store():
    """Native engine when the toolchain builds, Python fallback
    otherwise (or RAY_TPU_NATIVE_KV=0 to force the fallback)."""
    import os

    from ray_tpu._private.gcs import KVStore

    if os.environ.get("RAY_TPU_NATIVE_KV", "1") != "1":
        return KVStore()
    try:
        from ray_tpu._native import load

        lib = load()
        if lib is not None and hasattr(lib, "gcs_kv_create"):
            return NativeKVStore(lib)
    except Exception:  # noqa: BLE001 — fall back, never fail init
        pass
    return KVStore()
