"""Framework configuration flag system.

TPU-native analogue of the reference's RAY_CONFIG macro table
(reference: src/ray/common/ray_config_def.h — 217 entries, overridable via
RAY_* env vars and the driver's _system_config). Here flags are declared
once in ``_DEFAULTS``; every flag is overridable via ``RAY_TPU_<NAME>``
environment variables and via ``init(system_config={...})``.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

_DEFAULTS: dict[str, Any] = {
    # Scheduling.
    "num_cpus": os.cpu_count() or 1,
    "scheduler_spread_threshold": 0.5,
    "max_pending_lease_requests_per_scheduling_category": 10,
    "worker_lease_timeout_ms": 500,
    # Object store.
    "object_store_memory_mb": 2048,
    "object_store_full_delay_ms": 100,
    "inline_object_max_size_bytes": 100 * 1024,
    "object_spilling_threshold": 0.8,
    "object_spilling_dir": "/tmp/ray_tpu_spill",
    # Tasks.
    "max_task_retries": 0,
    "task_retry_delay_ms": 0,
    # Actors.
    "actor_max_restarts": 0,
    "actor_graceful_shutdown_timeout_s": 5.0,
    # Health checking.
    "health_check_period_ms": 1000,
    "health_check_failure_threshold": 5,
    # Lineage reconstruction.
    "lineage_table_max_entries": 10_000,
    # Metrics.
    "metrics_report_interval_ms": 2000,
    # Logging.
    "log_level": "INFO",
    # Multiprocess worker pool.
    "worker_pool_size": 0,  # 0 => disabled (thread workers); N>0 => N processes
    "worker_startup_timeout_s": 30.0,
    # Native shared-memory arena (plasma-lite, _native/plasma_store.cpp).
    "object_arena_bytes": 64 * 1024 * 1024,  # 0 => segment-per-object only
    "object_arena_max_object_bytes": 1024 * 1024,
    # Watermark-driven spill tier (spill_manager.py): when a store's
    # resident bytes cross spill_high_watermark x capacity, an async
    # spiller moves unpinned/unleased primaries to checksummed files
    # under $RAY_TPU_SESSION_DIR/spill/<pid>/ and frees the memory
    # (and any shm/arena twin), restoring transparently on read —
    # working sets >> RAM degrade to disk instead of shedding.
    # Disarmed (spill_enabled=0), every site costs one
    # module-attribute branch (spill_manager.SPILL_ON) and the stores
    # keep their legacy inline cap-based spilling byte-identically.
    "spill_enabled": True,
    "spill_high_watermark": 0.85,   # wake the spiller above this
    "spill_low_watermark": 0.60,    # spill down to this (hysteresis)
    "spill_fsync": False,           # fsync each file before rename
    "spill_min_object_kb": 16,      # smallest spillable object
    # Disk-full backoff: after a failed spill write, admission treats
    # store pressure as unrelievable (typed shed) for this long
    # instead of hammering a full disk or crashing the daemon.
    "spill_disk_full_backoff_s": 5.0,
    # Memory monitor (reference: memory_monitor.h kill-on-pressure).
    "memory_usage_threshold": 0.95,
    "memory_monitor_refresh_ms": 1000,  # 0 => disabled
    "task_oom_retries": 3,  # retry budget for monitor-killed tasks
    # Worker log capture + driver-side echo (reference: log_monitor.py).
    "log_to_driver": True,
    # Placement groups.
    "placement_group_commit_timeout_s": 30.0,
    # Worker-node daemon object store (primary copies of task/actor
    # results). Over the cap, the oldest primaries spill to disk and
    # restore on fetch (reference: local_object_manager.h:110 spilling).
    "node_store_primary_limit_mb": 4096,
    "node_store_spill_dir": "/tmp/ray_tpu_node_spill",
    # Owner-death GC on daemons: blobs/actors of a driver whose client
    # endpoint stays unreachable past the grace period are swept
    # (reference: owner-death cleanup in the ownership protocol,
    # reference_count.h:61). 0 disables the sweeper.
    "owner_sweep_period_ms": 5000,
    "owner_dead_grace_s": 15.0,
    # Node-to-node transfer plane (reference: the chunked Push/Pull
    # sizing knobs among the 217 RAY_CONFIG entries).
    "executor_inline_reply_kb": 256,   # results <= this ship inline
    "fetch_chunk_kb": 4096,            # chunk size of node pulls
    "node_pull_cache_mb": 512,         # pulled-copy cache per daemon
    # Actor scheduling (reference: actor creation/restart timeouts).
    "actor_lease_timeout_s": 300.0,
    "actor_restart_relocate_timeout_s": 120.0,
    # End-to-end deadlines (overload-control plane). A task submitted
    # without an explicit ``_deadline_s`` inherits this budget; 0
    # disables. The absolute deadline is stamped on the TaskSpec and
    # checked at every pipeline stage (ring flush, dispatcher claim,
    # daemon admission, worker frame pickup) — expired work seals
    # TaskTimeoutError instead of executing.
    "task_default_deadline_s": 0.0,
    # Admission control / load shedding. Queue-depth cap on the
    # driver's dispatcher (waiting + ready + running); over it, the
    # submit ring blocks deadline-free flushes (bounded backpressure)
    # and sheds deadline-armed submits with SystemOverloadedError.
    # Daemons apply the same cap to their admitted-reservation count.
    # 0 = unlimited.
    "admission_max_queue_depth": 0,
    # Host-memory fraction above which admission sheds instead of
    # queueing (fed by memory_monitor's /proc/meminfo reader, checked
    # with a short memo so the hot path never re-reads per task).
    # 0 disables.
    "admission_memory_watermark": 0.0,
    # RPC plane.
    "rpc_io_pool_workers": 16,         # pooled short-call dispatch
    # Locality- and load-aware placement (closing the observability
    # loop: pick_node consumes the object directory + the heartbeat-
    # shipped node-stats feed). Disarmed, every site costs one
    # module-attribute branch (scheduler.LOCALITY_ON) and pick_node is
    # byte-identical to the classic hybrid policy.
    "locality_aware_scheduling": True,
    # Arguments at/above this size count toward byte-weighted locality
    # scoring (small args are cheaper to move than to chase).
    "locality_min_arg_kb": 64,
    # Node-stats entries older than this (GCS receipt age + local
    # decay) stop contributing to the load score: a wedged daemon that
    # stops heartbeating must not keep looking idle to the scorer.
    "sched_stats_stale_s": 6.0,
    # Straggler speculation (driver-side watcher): an in-flight task
    # whose elapsed wall exceeds speculation_p99_factor x the
    # per-function p99 from the perf plane gets a speculative copy
    # re-dispatched to a different node; first seal wins, the loser is
    # cancelled best-effort. Off by default (speculation re-executes
    # work); disarmed cost is one module-attribute branch
    # (speculation.SPEC_ON) per site.
    "speculation_enabled": False,
    "speculation_p99_factor": 3.0,
    # Max speculative copies per task (bounds wasted re-execution).
    "speculation_max_copies": 1,
    # Completed-sample floor before the per-function p99 is trusted.
    "speculation_min_samples": 8,
    # Watcher sweep cadence.
    "speculation_watch_period_ms": 200,
    # Shared retry/backoff/deadline policy for IDEMPOTENT control-plane
    # calls (rpc.call_with_retry — heartbeats, fetch_plan, GCS reads).
    # Non-idempotent submits never ride it: a maybe-executed failure
    # must surface, not silently re-execute.
    "rpc_retry_attempts": 3,
    "rpc_retry_base_ms": 50,           # exponential backoff base
    "rpc_retry_deadline_s": 15.0,      # overall per-call retry budget
    # Per-destination circuit breaker riding the same retry policy: a
    # destination failing this many CONSECUTIVE logical calls (each
    # call_with_retry invocation counts at most once, however many
    # attempts it burned) opens its breaker — further calls fail fast
    # with a retryable RpcError instead of eating whole retry budgets
    # against a sick node. After rpc_breaker_reset_s one half-open
    # probe is let through; success closes the breaker, failure
    # re-opens it. rpc_breaker_failures=0 disables.
    "rpc_breaker_failures": 5,
    "rpc_breaker_reset_s": 5.0,
    # Deterministic fault injection (chaos.py); "" disables — every
    # injection site then costs one module-attribute branch. Spec:
    # "seed=42,rpc.sever=0.1,rpc.drop_frame=0.05x3,...".
    "chaos": "",
    # Pipelined transport (reference: gRPC completion queues carry many
    # in-flight calls per connection, src/ray/rpc/client_call.h).
    "rpc_pipeline_depth": 8,           # in-flight chunk fetches per pull
    "rpc_batch_flush_ms": 0.0,         # coalescing linger; 0 = natural
    "rpc_batch_max_entries": 128,      # max calls per batched frame
    # Pipelined task execution (batched dispatch -> execute_task_batch
    # -> multi-task worker leases -> grouped completion replies).
    # Tasks per execute_task_batch RPC. Raised 32 -> 128 with fused
    # execution: the dispatcher's batch-fill over-subscription now
    # actually reaches this depth (claims were capped at per-node free
    # slots before), and on a many-node single-core box every batch
    # costs a daemon wake — deeper batches amortize it. The fill
    # budget adapts to backlog//nodes, so small bursts still spread.
    "dispatch_batch_max": 128,
    "worker_pipeline_depth": 4,        # frames in flight per worker lease
    # Fused in-daemon execution: runs of tiny DEFAULT tasks inside an
    # execute_task_batch RPC run directly on the daemon's dispatch
    # thread — no worker-pipe hop, no per-task pickle round trip —
    # sealed back as grouped completions. Ref-bearing / TPU /
    # runtime_env / dedicated-worker entries always take the classic
    # or pipelined worker path. Disarmed (fused_execution=0), every
    # site costs one module-attribute branch (node_executor.FUSED_ON)
    # and the batch path is byte-identical to the worker pipeline.
    "fused_execution": True,
    # Per-RPC fused-run budget: at most this many tasks fuse per batch
    # RPC, and once the run's wall clock exceeds the budget the
    # remaining fused-eligible entries fall back to the pipelined
    # worker path (fused_fallbacks counter) — one long task cannot
    # wedge the daemon's reply stream for the whole batch.
    "fused_max_run_tasks": 256,
    "fused_run_wall_budget_s": 0.25,
    # Raw-bytes framing for small immutable args/results (ints,
    # floats, bools, str/bytes, flat tuples/dicts of them): a compact
    # tag-length encoding written into a thread-local scratch arena
    # replaces the pickle round trip on BOTH ends of the worker pipe
    # and the fused path. Disarmed (raw_framing=0), every encode site
    # costs one module-attribute branch (serialization.RAW_ON) and
    # frames are byte-identical pickles; decoding raw frames stays
    # supported either way (the sentinel header length cannot collide
    # with a pickled frame).
    "raw_framing": True,
    # Pipelined task SUBMISSION (driver-side submit ring): .remote()
    # allocates ids/refs inline and pushes a record onto a bounded
    # ring; a dedicated submitter thread drains flushes through ONE
    # store/lineage/GCS/dispatcher pass each. Disabled, every submit
    # takes the classic inline path.
    "submit_pipeline": True,
    "submit_ring_size": 65536,         # ring capacity; full => backpressure
    "submit_flush_max": 1024,          # records drained per flush pass
    # Sharded driver dispatch + columnar submit records
    # (dispatch_lanes.py): in connected mode, DEFAULT fused-eligible
    # submits (scalar args, one return, no deadline/PG/affinity) skip
    # per-task _SubmitRecord/TaskSpec/TaskEvent/lineage objects — a
    # flush builds ONE columnar group per RemoteFunction (parallel
    # id/args columns off the frozen call template) and hands it to N
    # dispatch lanes, each with its own lock domain and ready queue;
    # the cluster ledger is the only shared structure, acquired once
    # per flush (ClusterState.acquire_batch), and get-less completions
    # seal through a counter-only fast path. Disarmed
    # (driver_sharded_dispatch=0), every submit takes the classic ring
    # path byte-identically; each site costs one module-attribute
    # branch (dispatch_lanes.SHARD_ON).
    "driver_sharded_dispatch": True,
    # Dispatch lanes (threads) the columnar groups shard across, keyed
    # by admission signature. More lanes overlap RPC waits to more
    # nodes; on a single-core box 2 is enough to keep one lane filling
    # while another drains replies.
    "dispatch_lanes": 2,
    # P2P chunked broadcast (reference: the object manager's chunked
    # Push/Pull fans transfers out peer-to-peer via the directory).
    "broadcast_chunk_fanout": 4,       # peer sources used per pull
    "broadcast_min_p2p_chunks": 4,     # smaller objects pull owner-only
    "node_relay_cache_mb": 4096,       # completed relay copies kept
    # Same-host zero-copy plane: co-hosted daemons map each other's
    # shared memory (dedicated segments / the native arena) instead of
    # chunk-pulling bytes over RPC (reference: plasma is host-shared by
    # design, object_manager/plasma/store_runner.h).
    "same_host_plane": True,           # enable same-host mapping
    # Objects at/above this are served to same-host peers by a named
    # segment the peer maps zero-copy; below it the peer does a single
    # memcpy out of the holder's arena/segment (map-vs-memcpy split:
    # small objects aren't worth a per-consumer mapping).
    "same_host_map_min_kb": 1024,
    # Owner-side pin leases outlive this only while the holder still
    # answers pings; a dead puller's pins are swept afterwards.
    "same_host_pin_ttl_s": 30.0,
    # Driver-side node table: absent-but-pinging nodes survive this many
    # consecutive sync passes before being dropped (head amnesia grace).
    "node_amnesia_max_passes": 5,
    # Head control plane.
    "gcs_heartbeat_timeout_s": 10.0,   # node declared dead after this
    # Durable control plane (gcs_persistence.py): the head persists
    # its FULL hot set — KV, jobs, node table, actor registry, object
    # directory incl. spilled marks, placement groups — as a
    # checksummed snapshot plus a length+CRC32-framed WAL between
    # snapshots, with torn-tail truncation and seq-gated replay on
    # restart. Disarmed (gcs_persistence=0) the head keeps the legacy
    # {kv, jobs} raw-pickle snapshot byte-identically and mints no
    # epoch.
    "gcs_persistence": True,
    # Full-snapshot cadence while armed; between snapshots every
    # mutation is WAL-durable, so this bounds restart replay length,
    # not durability.
    "gcs_snapshot_interval_s": 30.0,
    # WAL size that forces an early snapshot + rotate.
    "gcs_wal_max_mb": 64,
    # fsync each WAL append / snapshot (durability vs latency; the
    # default flushes to the OS only — a head SIGKILL loses nothing,
    # a host power cut may lose the tail).
    "gcs_wal_fsync": False,
    # Epoch fencing (requires gcs_persistence): the head mints a
    # persisted incarnation number each start; every RPC reply and
    # heartbeat carries it, stale-epoch writes are rejected typed
    # (StaleEpochError, retryable after re-sync) so a partitioned
    # daemon or lingering old head can never double-register a node,
    # resurrect a dead actor, or corrupt the object directory.
    "gcs_epoch_fencing": True,
    # Sharded hot tables (gcs_shard.py): split the head's object
    # directory, task events and node-stats/stage-latency aggregation
    # across N in-head shard domains — each with its own lock domain,
    # RGW1 WAL + snapshot segment and persisted incarnation epoch, so
    # one shard crash-restarts (replaying only its WAL, fencing its
    # stale writers typed) while the others keep serving. Default 1
    # keeps the PR 12 single-WAL layout byte-identically; changing the
    # count over an existing layout is refused typed (ReshardError),
    # never silently misrouted.
    "gcs_shards": 1,
    # Degraded mode: writes to a stalled/partitioned shard are
    # WAL-durable immediately and queue for in-memory apply until the
    # shard heals; past this cap they shed typed
    # (SystemOverloadedError) instead of queueing unboundedly.
    "gcs_shard_max_queued_writes": 512,
    # LLM inference engine (serve/llm_engine): paged KV-cache
    # continuous batching with prefill/decode scheduling. Disarmed
    # (llm_paged_engine=0), LLMEngineServer falls back to the legacy
    # slot-per-request llm.LLMServer byte-identically; every gated
    # site costs one module-attribute branch (llm_engine PAGED_ON).
    "llm_paged_engine": True,
    # Tokens per KV block (the page size of the paged cache): small
    # blocks waste less memory on ragged tails, large blocks shrink
    # the block tables. Must divide into max_seq_len cleanly for a
    # full-length sequence.
    "llm_block_size": 16,
    # Prefill chunk length: a long prompt prefills in fixed chunks of
    # this many tokens, interleaved with decode steps, so one long
    # prompt cannot stall in-flight streams. Also the jit-cache bound:
    # ONE prefill program total (every chunk pads to this shape).
    "llm_prefill_chunk": 32,
    # Bounded engine waiting queue: requests past this depth shed
    # typed (CacheExhaustedError -> SystemOverloadedError path ->
    # HTTP 503) instead of queueing unboundedly.
    "llm_max_waiting": 64,
    # Serve routers push their live latency_stats() (p50/p99) to the
    # controller at most this often — the feed the latency-driven
    # replica autoscaler consumes. 0 disables the push.
    "serve_latency_report_s": 1.0,
    # Worker pipe transport.
    "worker_inline_result_kb": 64,     # pool results <= this inline
    # Distributed tracing plane (util/tracing.py). Disabled, every
    # instrumentation site costs one module-attribute branch
    # (tracing.TRACE_ON — same discipline as chaos.ACTIVE).
    "tracing_enabled": False,
    # Per-process span buffer cap (local records AND the remote-shipping
    # outbox); overflow increments the dropped-span counter.
    "tracing_buffer_max_spans": 4096,
    # Per-stage TaskEvent timestamps (submit/dispatch/rpc/admit/worker/
    # exec/seal) — stamped only while tracing is enabled; this gates
    # them off independently if the stage map itself is unwanted.
    "tracing_stage_timestamps": True,
    # Always-on performance plane (perf_plane.py): stage-latency
    # histograms + per-task resource attribution, recorded WITHOUT
    # tracing being armed and shipped on heartbeats. Disarmed, every
    # site costs one module-attribute branch (perf_plane.PERF_ON);
    # RAY_TPU_PERF_PLANE=0 disarms a whole cluster via the daemon env.
    "perf_plane": True,
    # Crash flight recorder (flight_recorder.py): bounded per-process
    # event ring, persisted to the session dir by daemons so a
    # SIGKILLed process leaves its last N events for `ray_tpu debug`.
    "flight_recorder_events": 512,
    # Daemon-side ring-flush period (seconds); 0 = dump-on-demand only.
    "flight_recorder_flush_s": 2.0,
    # Runtime lock-order witness (lock_witness.py): armed, the hot
    # modules' locks record a per-thread held-set and a global
    # acquisition-order graph; a cycle (two lock classes taken in both
    # orders — a potential deadlock) flight-records both stacks and
    # raises LockOrderError. Tier-1 and the chaos soak arm it
    # (RAY_TPU_LOCK_WITNESS=1); production stays disarmed — the
    # factories then return plain threading objects, so the acquire
    # path is byte-identical to an unwitnessed build. Bench envelope
    # refreshes record the state and test_bench_regression refuses a
    # witness-armed refresh.
    "lock_witness": False,
    # Cluster history plane (metrics_history.py): head-side
    # fixed-interval ring-buffer store that delta-encodes the per-node
    # cumulative heartbeat stats into per-interval samples, plus the
    # rule-driven health watchdog sweeping it. Disarmed
    # (metrics_history=0 / RAY_TPU_METRICS_HISTORY=0), the head's
    # monitor tick pays one module-attribute branch
    # (metrics_history.HISTORY_ON) and the metrics_history /
    # cluster_health RPCs answer armed=False.
    "metrics_history": True,
    # Sampling cadence: one delta-encoded sample per node per interval.
    "metrics_history_interval_s": 2.0,
    # Bounded retention window; ring capacity = retention / interval.
    # Node series idle past this are evicted.
    "metrics_history_retention_s": 600.0,
    # Health watchdog rule thresholds (metrics_history.HEALTH_RULES).
    # Rates evaluate over this trailing window.
    "health_window_s": 30.0,
    # overload: admission-shed rate past this, sustained over >= 2
    # intervals (one burst is backpressure, not a verdict).
    "health_overload_shed_per_s": 0.5,
    # breaker_storm: circuit-breaker opens inside one window.
    "health_breaker_storm_opens": 3.0,
    # spill_thrash: spill+restore churn rate past this WHILE restore
    # p50 is past health_spill_restore_p50_ms.
    "health_spill_churn_per_s": 2.0,
    "health_spill_restore_p50_ms": 50.0,
    # wedged_node: node-stats receipt age (age_s) past this — the
    # daemon stopped heartbeating but is not yet declared dead.
    "health_wedged_age_s": 10.0,
    # stale_shard: a GCS shard's stall age past this serves stale
    # reads and queued writes (history for its domain is degraded).
    "health_stale_shard_age_s": 3.0,
    # fused_fallback_spike: fused-run fallbacks-to-pipeline per second.
    "health_fused_fallback_per_s": 1.0,
    # Native (C++) daemon blob store (node_store.cpp); falls back to
    # the Python store when the toolchain/library is unavailable.
    "node_store_native": True,
    # Native (C++) GCS KV storage engine (gcs_kv.cpp) for HEAD
    # processes; same fallback behavior.
    "gcs_kv_native": True,
}


class Config:
    """Process-wide flag table with env-var and runtime overrides."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values = dict(_DEFAULTS)
        self._apply_env_overrides()

    def _apply_env_overrides(self):
        for key, default in _DEFAULTS.items():
            env_key = "RAY_TPU_" + key.upper()
            raw = os.environ.get(env_key)
            if raw is None:
                continue
            self._values[key] = _coerce(raw, type(default))

    def update(self, overrides: dict[str, Any] | str | None):
        if not overrides:
            return
        if isinstance(overrides, str):
            overrides = json.loads(overrides)
        with self._lock:
            for key, value in overrides.items():
                if key not in _DEFAULTS:
                    raise KeyError(f"Unknown system config key: {key!r}")
                self._values[key] = value

    def get(self, key: str) -> Any:
        with self._lock:
            return self._values[key]

    def peek(self, key: str) -> Any:
        """Lock-free read for per-call hot paths (the columnar submit
        eligibility check runs per ``.remote()``). Safe: ``_values``
        maps a fixed key set and ``update``/``reset`` replace values
        per key under the GIL — a peek sees either the old or the new
        value, never a torn one."""
        return self._values[key]

    def __getattr__(self, key: str) -> Any:
        if key.startswith("_"):
            raise AttributeError(key)
        try:
            return self.get(key)
        except KeyError:
            raise AttributeError(key) from None

    def reset(self):
        with self._lock:
            self._values = dict(_DEFAULTS)
            self._apply_env_overrides()


def _coerce(raw: str, typ: type) -> Any:
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(raw)
    if typ is float:
        return float(raw)
    return raw


GLOBAL_CONFIG = Config()
